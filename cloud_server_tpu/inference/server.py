"""Serving layer: slot-based continuous batching over the inference engine.

The TPU-first serving design: ONE persistent KV cache of static shape
(L, max_slots, max_len, KH, Dh) lives on device for the server's lifetime.
Each in-flight request owns a *slot* (a batch row). Admission prefills the
prompt into its slot; a single jitted decode advances ALL active slots one
token per call. Requests join and leave between decode steps — new work
never waits for old work to finish (continuous batching), and shapes never
change (no recompiles, no cache reallocation).

Two jitted functions do all device work:
  * admit:  one batched prefill (G, Pb) for the whole admission burst →
    scatter the slots' cache regions + sample the first tokens. Padded
    lengths are bucketed (next power of two) and the group row count is
    padded to a power of two, so compiles are bounded; slot indices are
    traced (no recompiles on slot choice).
  * decode: one step over the full slot batch. Inactive slots are masked —
    their length doesn't advance and they emit pad. Their cache writes
    land at their frozen length position, which any later occupant
    overwrites before it can ever be attended (write-at-pos happens before
    attention reads pos), so no cross-request leakage is possible.

The host side is a small scheduler: a pending queue, per-request token
accumulation, EOS / max-token completion, optional streaming callbacks.
One device_get of the sampled-token block per scheduler iteration is the
only host↔device sync; with `decode_chunk > 1` (multi-token scheduling)
that iteration covers up to decode_chunk tokens per slot via an on-device
`lax.scan`, amortising dispatch latency at the cost of up to chunk-1
steps of admission latency.

Sharding: wrap `params` (and the server's jits inherit via input
shardings) with tp/fsdp NamedShardings for multi-chip serving; the slot
batch rides (dp, fsdp) exactly like training batches.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import math
import os
import threading
import time
import uuid
from functools import partial
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from cloud_server_tpu.config import InferConfig, ModelConfig
from cloud_server_tpu.inference import engine
from cloud_server_tpu.inference.iteration_profile import OVERLAP_PHASES
from cloud_server_tpu.inference.sampling import (
    SamplingParams, SamplingRows, make_rows, sample_logits,
    sample_logits_rows, set_rows, zero_rows)
from cloud_server_tpu.utils.serving_metrics import ServingMetrics


def _token_logprobs(logits: jnp.ndarray, toks: jnp.ndarray) -> jnp.ndarray:
    """log P(tok) under the model's raw (pre-filter) distribution — the
    one serving-API logprob convention, shared by admission and decode."""
    return jnp.take_along_axis(jax.nn.log_softmax(logits, axis=-1),
                               toks[:, None], axis=-1)[:, 0]


class SlotState:
    """Device-resident server state (a pytree)."""

    def __init__(self, k, v, length, last_token, active,
                 k_scale=None, v_scale=None, samp=None,
                 prompt_mask=None, out_counts=None):
        self.k = k                    # (L, B, max_len, KH, Dh)
        self.v = v
        self.length = length          # (B,) int32
        self.last_token = last_token  # (B,) int32
        self.active = active          # (B,) bool
        self.k_scale = k_scale        # int8 kv cache only, else None
        self.v_scale = v_scale
        # per-request sampling state: parameter rows, prompt-token
        # presence (B, V) bool and generated-token counts (B, V) int32
        # for penalties. Rows are written by every admission; the count
        # buffers are None until the FIRST penalty-using request
        # materializes them (penalty-free deployments never pay their
        # HBM or scatter cost; pre-materialization slots carry neutral
        # penalties, for which the buffers are read-irrelevant) and then
        # advance only in rows-mode decode dispatches.
        self.samp = samp              # SamplingRows of (B,) arrays
        self.prompt_mask = prompt_mask
        self.out_counts = out_counts

    def tree_flatten(self):
        return (self.k, self.v, self.length, self.last_token,
                self.active, self.k_scale, self.v_scale, self.samp,
                self.prompt_mask, self.out_counts), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    SlotState, SlotState.tree_flatten, SlotState.tree_unflatten)


def init_slot_state(cfg: ModelConfig, max_slots: int,
                    max_len: int) -> SlotState:
    cache = engine.init_cache(cfg, max_slots, max_len)
    return SlotState(
        k=cache.k, v=cache.v, length=cache.length,
        last_token=jnp.zeros((max_slots,), jnp.int32),
        active=jnp.zeros((max_slots,), bool),
        k_scale=cache.k_scale, v_scale=cache.v_scale,
        samp=zero_rows(max_slots), prompt_mask=None, out_counts=None)


def _prompt_presence(token_rows: jnp.ndarray, true_lens: jnp.ndarray,
                     vocab: int) -> jnp.ndarray:
    """(G, Pb) token rows + true lengths -> (G, vocab) bool presence."""
    g, pb = token_rows.shape
    rowi = jnp.arange(g)
    valid = jnp.arange(pb)[None, :] < true_lens[:, None]
    cols = jnp.where(valid, token_rows, vocab)
    return jnp.zeros((g, vocab), bool).at[rowi[:, None], cols].set(
        True, mode="drop")


def _admit_sampling_state(state: SlotState, samp_rows: SamplingRows,
                          slots: jnp.ndarray, pm_rows, first_toks):
    """Shared admission bookkeeping for per-request sampling: write the
    group's parameter rows and — when the penalty buffers have been
    materialized (`pm_rows` from `_prompt_presence`, else None) — the
    slots' prompt-presence masks and generated-token counts reset to the
    first sampled token.

    Returns (samp, prompt_mask, out_counts)."""
    samp = set_rows(state.samp, slots, samp_rows)
    if state.prompt_mask is None:
        return samp, None, None
    g, v = pm_rows.shape
    oc = jnp.zeros((g, v), jnp.int32).at[jnp.arange(g), first_toks].add(1)
    return (samp,
            state.prompt_mask.at[slots].set(pm_rows, mode="drop"),
            state.out_counts.at[slots].set(oc, mode="drop"))


@partial(jax.jit,
         static_argnames=("cfg", "infer_cfg", "use_rows", "use_bias"),
         donate_argnums=(1,))
def _admit_batch(params, state: SlotState, prompts: jnp.ndarray,
                 true_lens: jnp.ndarray, slots: jnp.ndarray, rng: jax.Array,
                 samp_rows: SamplingRows, *, cfg: ModelConfig,
                 infer_cfg: InferConfig, use_rows: bool = False,
                 use_bias: bool = False):
    """Prefill G prompts (G, Pb) into `slots` (G,); sample first tokens.

    A whole admission burst is ONE batched prefill (full MXU batch) instead
    of G sequential (1, Pb) prefills. Rows whose slot index is out of range
    (>= max_slots) are padding — `mode="drop"` scatters discard them — so
    one compilation serves any group of size <= G. `slots` values are
    traced, so slot choice never recompiles; only (G, Pb) does (both are
    bucketed by the caller).

    `use_rows` (static) switches first-token sampling to the per-request
    SamplingRows path; the rows themselves are always recorded so later
    rows-mode decodes see this group's parameters.

    Returns (state', first_tokens (G,), their logprobs (G,) f32).
    """
    g, pb = prompts.shape
    tmp = engine.init_cache(cfg, g, pb)
    logits, tmp = engine.prefill(params, prompts, cfg, tmp, true_lens)
    has_pen = state.prompt_mask is not None
    pm_g = (_prompt_presence(prompts, true_lens, logits.shape[-1])
            if has_pen else None)
    if use_rows:
        # first generated token: no output counts yet
        toks = sample_logits_rows(
            logits, samp_rows, true_lens, prompt_mask=pm_g,
            out_counts=(jnp.zeros_like(logits, jnp.int32)
                        if has_pen else None),
            eos_id=infer_cfg.eos_token_id, use_bias=use_bias)
    else:
        toks = sample_logits(logits, rng, infer_cfg)  # (G,)
    lps = _token_logprobs(logits, toks)  # (G,)

    k = state.k.at[:, slots, :pb].set(tmp.k, mode="drop")
    v = state.v.at[:, slots, :pb].set(tmp.v, mode="drop")
    k_scale = v_scale = None
    if state.k_scale is not None:
        k_scale = state.k_scale.at[:, slots, :pb].set(tmp.k_scale,
                                                      mode="drop")
        v_scale = state.v_scale.at[:, slots, :pb].set(tmp.v_scale,
                                                      mode="drop")
    samp, pmask, counts = _admit_sampling_state(
        state, samp_rows, slots, pm_g, toks)
    return SlotState(
        k=k, v=v,
        length=state.length.at[slots].set(true_lens, mode="drop"),
        last_token=state.last_token.at[slots].set(toks, mode="drop"),
        active=state.active.at[slots].set(True, mode="drop"),
        k_scale=k_scale, v_scale=v_scale, samp=samp, prompt_mask=pmask,
        out_counts=counts), toks, lps


@partial(jax.jit,
         static_argnames=("cfg", "infer_cfg", "use_rows", "use_bias"),
         donate_argnums=(1,))
def _admit_batch_prefixed(params, state: SlotState, prefix_kv,
                          remainders: jnp.ndarray,
                          true_lens: jnp.ndarray, slots: jnp.ndarray,
                          rng: jax.Array, samp_rows: SamplingRows,
                          prefix_toks: jnp.ndarray, *, cfg: ModelConfig,
                          infer_cfg: InferConfig, use_rows: bool = False,
                          use_bias: bool = False):
    """Admission via a cached common-prefix KV (prefix caching).

    The prefix's cache entries (prefix_kv: dict with k/v (L, 1, P0, KH,
    Dh) and optional k_scale/v_scale) are broadcast into a temp cache and
    only the REMAINDER tokens (G, Rb) run through the model — as a
    `verify_step` continuation at offset prefix_len, so the remainder
    attends to the cached prefix exactly as a full prefill would. Cost
    per admission drops from O(P0 + R) to O(R) model FLOPs.

    Returns (state', first_tokens (G,), logprobs (G,)).
    """
    g, rb = remainders.shape
    p0 = prefix_kv["k"].shape[2]
    tmp = engine.init_cache(cfg, g, p0 + rb)

    def put_prefix(buf, pre):
        pre = jnp.broadcast_to(pre, (pre.shape[0], g) + pre.shape[2:])
        return lax.dynamic_update_slice(
            buf, pre.astype(buf.dtype), (0, 0, 0, 0, 0))

    k = put_prefix(tmp.k, prefix_kv["k"])
    v = put_prefix(tmp.v, prefix_kv["v"])
    ks = vs = None
    if tmp.k_scale is not None:
        ks = put_prefix(tmp.k_scale, prefix_kv["k_scale"])
        vs = put_prefix(tmp.v_scale, prefix_kv["v_scale"])
    lengths0 = jnp.full((g,), p0, jnp.int32)  # static prefix width
    tmp = engine.KVCache(k, v, lengths0, ks, vs)

    logits, tmp = engine.verify_step(params, remainders, cfg, tmp)
    last = logits[jnp.arange(g), true_lens - 1]  # (G, V)
    new_lens = p0 + true_lens
    # the slot's true prompt is prefix + remainder: build the padded
    # full-prompt rows once and share them with the sampling-state scatter
    full_rows = jnp.concatenate(
        [jnp.broadcast_to(prefix_toks[None, :], (g, p0)), remainders],
        axis=1)
    has_pen = state.prompt_mask is not None
    pm_g = (_prompt_presence(full_rows, new_lens, last.shape[-1])
            if has_pen else None)
    if use_rows:
        toks = sample_logits_rows(
            last, samp_rows, new_lens, prompt_mask=pm_g,
            out_counts=(jnp.zeros_like(last, jnp.int32)
                        if has_pen else None),
            eos_id=infer_cfg.eos_token_id, use_bias=use_bias)
    else:
        toks = sample_logits(last, rng, infer_cfg)
    lps = _token_logprobs(last, toks)

    width = p0 + rb
    k = state.k.at[:, slots, :width].set(tmp.k, mode="drop")
    v = state.v.at[:, slots, :width].set(tmp.v, mode="drop")
    k_scale = v_scale = None
    if state.k_scale is not None:
        k_scale = state.k_scale.at[:, slots, :width].set(tmp.k_scale,
                                                         mode="drop")
        v_scale = state.v_scale.at[:, slots, :width].set(tmp.v_scale,
                                                         mode="drop")
    samp, pmask, counts = _admit_sampling_state(
        state, samp_rows, slots, pm_g, toks)
    return SlotState(
        k=k, v=v,
        length=state.length.at[slots].set(new_lens, mode="drop"),
        last_token=state.last_token.at[slots].set(toks, mode="drop"),
        active=state.active.at[slots].set(True, mode="drop"),
        k_scale=k_scale, v_scale=v_scale, samp=samp, prompt_mask=pmask,
        out_counts=counts), toks, lps


def _decode_core(params, state: SlotState, rng: jax.Array,
                 cfg: ModelConfig, infer_cfg: InferConfig,
                 use_rows: bool = False, use_bias: bool = False):
    """One decode step over all slots; inactive slots are frozen."""
    cache = engine.KVCache(state.k, state.v, state.length,
                           state.k_scale, state.v_scale)
    logits, cache = engine.decode_step(params, state.last_token, cfg, cache)
    out_counts = state.out_counts
    if use_rows:
        # the sampled token sits at absolute position length + 1 (`last`
        # occupies `length`); admission folds the prompt length for the
        # first token, so positions never collide within a request
        tok = sample_logits_rows(logits, state.samp, state.length + 1,
                                 prompt_mask=state.prompt_mask,
                                 out_counts=out_counts,
                                 eos_id=infer_cfg.eos_token_id,
                                 use_bias=use_bias)
        if out_counts is not None:
            out_counts = out_counts.at[
                jnp.arange(tok.shape[0]), tok].add(
                    state.active.astype(jnp.int32))
    else:
        tok = sample_logits(logits, rng, infer_cfg)
    lp = _token_logprobs(logits, tok)
    tok = jnp.where(state.active, tok, infer_cfg.pad_token_id)
    length = jnp.where(state.active, cache.length, state.length)
    return SlotState(k=cache.k, v=cache.v, length=length, last_token=tok,
                     active=state.active, k_scale=cache.k_scale,
                     v_scale=cache.v_scale, samp=state.samp,
                     prompt_mask=state.prompt_mask,
                     out_counts=out_counts), (tok, lp)


@partial(jax.jit,
         static_argnames=("cfg", "infer_cfg", "use_rows", "use_bias"),
         donate_argnums=(1,))
def _decode(params, state: SlotState, rng: jax.Array, *, cfg: ModelConfig,
            infer_cfg: InferConfig, use_rows: bool = False,
            use_bias: bool = False):
    """Returns (state', (tokens (B,) int32, logprobs (B,) f32)) with pad
    in inactive rows."""
    return _decode_core(params, state, rng, cfg, infer_cfg, use_rows,
                        use_bias)


@partial(jax.jit, static_argnames=("cfg", "infer_cfg", "n_steps",
                                   "use_rows", "use_bias"),
         donate_argnums=(1,))
def _decode_chunk(params, state: SlotState, rng: jax.Array, *,
                  cfg: ModelConfig, infer_cfg: InferConfig, n_steps: int,
                  use_rows: bool = False, use_bias: bool = False):
    """n_steps decode steps in ONE dispatch (lax.scan on device).

    Multi-token scheduling: the host syncs (device_get of the sampled
    tokens) once per chunk instead of once per token, amortising dispatch
    and host<->device latency over n_steps tokens. The host discards any
    in-chunk tokens past a request's EOS / budget afterwards, so chunking
    trades at most n_steps - 1 wasted decode steps (and that much admission
    latency) for steady-state throughput.

    Returns (state', (tokens (n_steps, B) int32,
    logprobs (n_steps, B) f32)).
    """
    def body(st, r):
        return _decode_core(params, st, r, cfg, infer_cfg, use_rows,
                            use_bias)

    return lax.scan(body, state, jax.random.split(rng, n_steps))


@partial(jax.jit, donate_argnums=(0,))
def _deactivate(state: SlotState, slot: jnp.ndarray) -> SlotState:
    return SlotState(k=state.k, v=state.v, length=state.length,
                     last_token=state.last_token,
                     active=state.active.at[slot].set(False),
                     k_scale=state.k_scale, v_scale=state.v_scale,
                     samp=state.samp, prompt_mask=state.prompt_mask,
                     out_counts=state.out_counts)


class _StepTracer:
    """On-demand profiling of the next N scheduler iterations into a
    jax profiler trace (utils.tracing.capture_trace), armed from any
    thread (the HTTP /debug/trace endpoint) and driven by the
    scheduler's own step() — the capture window aligns exactly with
    iteration boundaries, so a dump shows whole dispatches, not
    fragments. Trace failures are swallowed with a stderr note: the
    profiler is process-global and telemetry must never take the
    scheduler (and every in-flight request) down with it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: tuple[int, str] | None = None
        self._cm = None
        self._left = 0

    def request(self, n_steps: int, logdir: str | os.PathLike) -> None:
        if n_steps <= 0:
            raise ValueError("trace step count must be positive")
        with self._lock:
            if self._pending is not None or self._cm is not None:
                raise ValueError("a trace capture is already in progress")
            self._pending = (int(n_steps), os.fspath(logdir))

    @property
    def active(self) -> bool:
        return self._pending is not None or self._cm is not None

    def step_start(self) -> None:
        with self._lock:
            if self._pending is None:
                return
            n, logdir = self._pending
            self._pending = None
            from cloud_server_tpu.utils import tracing
            try:
                cm = tracing.capture_trace(logdir)
                cm.__enter__()
            except Exception as exc:  # noqa: BLE001 — see class docstring
                import sys
                print(f"[server] trace capture failed to start: {exc!r}",
                      file=sys.stderr)
                return
            self._cm, self._left = cm, n

    def step_end(self) -> None:
        with self._lock:
            if self._cm is None:
                return
            self._left -= 1
            if self._left > 0:
                return
            cm, self._cm = self._cm, None
            try:
                cm.__exit__(None, None, None)
            except Exception as exc:  # noqa: BLE001
                import sys
                print(f"[server] trace capture failed to stop: {exc!r}",
                      file=sys.stderr)


class QueueFullError(RuntimeError):
    """submit() refused: the pending queue is at its configured bound.
    Backpressure, not failure — the HTTP front-end maps this to 429 so
    clients retry instead of piling unbounded host memory."""


@dataclasses.dataclass
class Request:
    """A generation request; thread-safe completion via `result()`."""

    prompt: list[int]
    max_new_tokens: int
    stream: Callable[[int], None] | None = None
    # per-request sampling controls (None = server defaults). Device-side
    # fields ride into dispatches as SamplingRows; stop / ignore_eos are
    # enforced host-side in emit_token.
    sampling: SamplingParams | None = None
    # the seed actually used for this request's device rows (the request's
    # own, or one drawn from the server's host RNG at submit) — stable
    # across preemption/re-admission
    seed_used: int = 0
    # multi-LoRA serving: registered adapter name (paged server)
    adapter: str | None = None
    # multi-tenant QoS (inference/qos.py): resolved tenant name, set at
    # submit. None = QoS disabled (no registry configured); requests on
    # a QoS-enabled server always carry a concrete name ("default" when
    # the client sent none).
    tenant: str | None = None
    # distributed tracing (inference/request_trace.py): the request's
    # RequestTrace when head sampling selected it at submit, else None
    # (unsampled, or tracing disabled — zero cost either way)
    trace: object | None = None
    # tail-based retention: the provisional lightweight trace a
    # head-UNSAMPLED request carries when the recorder runs a tail
    # ring; judged (retain or forget) at finish. None when head-
    # sampled or tail retention is off.
    tail_trace: object | None = None
    # SLO class (inference/slo.py): the tenant's QoS priority class
    # name, resolved once at submit when SLO tracking is configured;
    # None otherwise (the tracker maps None onto its "default" entry)
    slo_class: str | None = None
    tokens: list[int] = dataclasses.field(default_factory=list)
    # log P(token) under the model's raw (pre-filter) distribution,
    # aligned with `tokens`
    logprobs: list[float] = dataclasses.field(default_factory=list)
    _done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    finish_reason: str | None = None  # "eos" | "length" | "error: ..."
    # request-level latency accounting (host wall clock, perf_counter):
    # submit_time set at submit(); one emit_times entry per token, set by
    # the scheduler at the host moment the token is surfaced. TTFT =
    # emit_times[0] - submit_time; inter-token latencies = diffs. Tokens
    # committed in one multi-token dispatch share one host moment —
    # near-zero ITLs inside a burst are real (burst delivery), the tail
    # percentiles are where scheduling stalls show.
    submit_time: float | None = None
    emit_times: list[float] = dataclasses.field(default_factory=list)
    # request deadline (absolute perf_counter moment, set at submit
    # from deadline_s or the tenant's QoS-class default): the
    # scheduler sweep cancels expired requests (finish_reason
    # "deadline", pages released through the normal path) and the
    # router stops failover retries past it. None = no deadline.
    deadline: float | None = None
    # lifecycle telemetry: a stable id (access logs / timelines) plus an
    # event trail of (name, perf_counter time) pairs appended at host
    # moments the scheduler already owns — submit, every (re-)admission,
    # first token, preempt-requeue, finish:<reason>. admit_time is the
    # FIRST admission (queue-wait semantics survive preemption).
    request_id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex[:12])
    admit_time: float | None = None
    events: list[tuple[str, float]] = dataclasses.field(
        default_factory=list)
    # client-side cancellation: the flag is checked by the scheduler;
    # `_on_cancel` is installed by the owning server at submit so a
    # still-PENDING request can be finished without waiting for a step
    _cancel: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    _on_cancel: Callable[["Request"], None] | None = None
    # failure interception (ReplicatedRouter failover): when a request
    # completes with an "error:" finish_reason, _complete offers it to
    # this hook BEFORE unblocking waiters; a True return means the
    # hook took ownership (a retry on another replica will complete
    # the request), so _done stays unset. None (the default, and
    # always for direct-server submits) keeps completion unchanged.
    _fail_handler: Callable[["Request"], bool] | None = None
    # completion callback invoked AFTER _done is set (the router's
    # retry-mirroring path); None for everything else.
    _on_done: Callable[["Request"], None] | None = None
    # True when an "error:" completion was caused by the REQUEST
    # itself (e.g. it can never fit the page pool) rather than the
    # replica: the router must neither retry it elsewhere — it fails
    # identically everywhere — nor count it against the replica's
    # circuit breaker.
    _request_fault: bool = False

    def cancel(self) -> None:
        """Abort this request. Pending requests finish immediately with
        finish_reason "cancelled"; a request mid-admission or decoding
        is torn down by its server's scheduler within one step (its
        slot and pages go back through the normal release path, so the
        KV it wrote stays reusable in the prefix cache). Idempotent;
        a no-op once the request has finished."""
        if self._done.is_set() or self._cancel.is_set():
            return
        self._cancel.set()
        if self._on_cancel is not None:
            self._on_cancel(self)

    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def record_event(self, name: str, t: float | None = None) -> None:
        self.events.append((name, time.perf_counter() if t is None
                            else t))

    def timeline(self) -> list[tuple[str, float]]:
        """The request's lifecycle events as (name, perf_counter time)
        pairs, in the order they happened: "submit", "admit" (repeated
        on re-admission after a preemption), "first_token",
        "preempt_requeue", "finish:<reason>". Token-level timing lives
        in `emit_times`."""
        return list(self.events)

    def latency_stats(self) -> dict | None:
        """TTFT and inter-token-latency summary (seconds); None until
        two tokens have been emitted."""
        if self.submit_time is None or len(self.emit_times) < 2:
            return None
        itl = [b - a for a, b in zip(self.emit_times, self.emit_times[1:])]
        itl.sort()

        def pct(p):
            return itl[min(len(itl) - 1, int(p * len(itl)))]

        return {"ttft": self.emit_times[0] - self.submit_time,
                "itl_p50": pct(0.50), "itl_p99": pct(0.99),
                "itl_max": itl[-1]}

    def result(self, timeout: float | None = None) -> list[int]:
        if not self._done.wait(timeout):
            raise TimeoutError("generation not finished")
        if self.finish_reason and self.finish_reason.startswith("error"):
            raise RuntimeError(f"generation failed: {self.finish_reason}")
        return self.tokens

    @property
    def done(self) -> bool:
        return self._done.is_set()


def resolve_seed(sampling: SamplingParams | None, host_rng, lock) -> int:
    """The request's own seed, or a fresh draw from the server's host
    RNG (under `lock`) — fixed once at submit so a preempted request
    re-admits with the same rows. Shared by both servers."""
    if sampling is not None and sampling.seed is not None:
        return int(sampling.seed)
    with lock:
        return int(host_rng.integers(0, 2 ** 32))


def emit_token(req: Request, token: int, logprob: float | None,
               infer_cfg: InferConfig) -> bool:
    """Record one generated token on `req`; True when the request just
    finished (eos / stop sequence / length). The single emit rule both
    servers share.

    Stop sequences are token-level: when the output's tail equals one of
    `req.sampling.stop`, the matched tokens are removed (OpenAI
    semantics) and finish_reason is "stop". The final token of a match is
    never streamed, but earlier tokens of the sequence were streamed as
    they arrived — the final `tokens` list is authoritative."""
    sp = req.sampling
    if token == infer_cfg.eos_token_id and not (sp and sp.ignore_eos):
        req.finish_reason = "eos"
        return True
    req.tokens.append(token)
    req.emit_times.append(time.perf_counter())
    if logprob is not None:
        # append before stream(): a consumer woken by the stream
        # callback may read logprobs[len(tokens)-1]
        req.logprobs.append(float(logprob))
    if sp and sp.stop:
        for s in sp.stop:
            ls = len(s)
            if len(req.tokens) >= ls and req.tokens[-ls:] == list(s):
                del req.tokens[-ls:]
                del req.emit_times[-ls:]
                # logprobs may cover only a PREFIX of tokens (the
                # logprob=None path appends nothing): drop exactly the
                # entries past the kept-token count — a blanket [-ls:]
                # would strip logprobs belonging to kept tokens
                drop = len(req.logprobs) - len(req.tokens)
                if drop > 0:
                    del req.logprobs[-drop:]
                req.finish_reason = "stop"
                return True
    if req.stream is not None:
        req.stream(token)
    if len(req.tokens) >= req.max_new_tokens:
        req.finish_reason = "length"
        return True
    return False


def _bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt of {n} tokens exceeds largest bucket "
                     f"{buckets[-1]}")


class InferenceServer:
    """Continuous-batching generation server.

    submit() is thread-safe and returns immediately; step() performs one
    scheduler iteration (admissions + one decode for all active slots).
    Run steps manually, or `serve_forever()` on a thread via start()/stop().
    """

    def __init__(self, params, cfg: ModelConfig, infer_cfg: InferConfig, *,
                 max_slots: int = 8, max_len: int = 1024,
                 prompt_buckets: Sequence[int] | None = None, seed: int = 0,
                 decode_chunk: int = 1, max_pending: int | None = None,
                 prefix_tokens: Sequence[int] | None = None,
                 prefix_remainder_cap: int = 1024,
                 metrics: ServingMetrics | None = None,
                 qos=None, tracing=None, slo=None,
                 iteration_profile=None, faults=None, anomaly=None,
                 overlap: bool | None = None):
        # Serving never needs f32 master weights: pre-cast float32 leaves to
        # the compute dtype once, instead of streaming 2x the bytes and
        # converting on every decode step. QTensor leaves stay quantized
        # (their .astype dequantizes — applied at use, not here).
        from cloud_server_tpu.models.quantization import QTensor
        target = jnp.dtype(cfg.dtype)

        def cast_leaf(w):
            if isinstance(w, QTensor):
                return w
            if getattr(w, "dtype", None) == jnp.float32 and w.ndim >= 1:
                return w.astype(target)
            return w

        self.params = jax.tree.map(
            cast_leaf, params, is_leaf=lambda x: isinstance(x, QTensor))
        if cfg.decode_attention_impl != "xla":
            # fail at construction, not deep inside the first jitted
            # decode trace (engine.decode_step raises the detailed error;
            # PagedInferenceServer validates eagerly the same way)
            raise ValueError(
                f"decode_attention_impl={cfg.decode_attention_impl!r} is "
                "not supported by the contiguous InferenceServer — the "
                "pallas decode kernel lives in the paged serving stack "
                "(inference.paged_server.PagedInferenceServer); use "
                "'xla' here")
        self.cfg = cfg
        self.infer_cfg = infer_cfg
        self.max_slots = max_slots
        self.max_len = max_len
        # Max decode steps per scheduler iteration (multi-token scheduling).
        # 1 = sync every token (lowest admission latency); larger values
        # amortise dispatch/host-sync overhead over the chunk. The actual
        # chunk never exceeds any active request's remaining budget, so no
        # request overshoots its max_new_tokens or the cache.
        self.decode_chunk = max(1, decode_chunk)
        if prompt_buckets is None:
            # powers of two, with max_len itself always the last bucket so
            # any prompt the cache can hold is admissible
            prompt_buckets = [b for b in itertools.takewhile(
                lambda b: b < max_len,
                (2 ** i for i in range(4, 31)))] + [max_len]
        self.prompt_buckets = sorted(prompt_buckets)
        if self.prompt_buckets[-1] > max_len:
            raise ValueError(
                f"largest prompt bucket ({self.prompt_buckets[-1]}) exceeds "
                f"max_len ({max_len}); the slot cache could not hold it")
        self.state = init_slot_state(cfg, max_slots, max_len)
        # Prefix caching: prefill the shared prompt prefix (e.g. a system
        # prompt) ONCE; admissions whose prompt extends it reuse the cached
        # KV and only run their remainder through the model.
        self._prefix: list[int] | None = None
        self._prefix_kv: dict | None = None
        self.prefix_remainder_cap = prefix_remainder_cap
        self.prefix_hits = 0
        self.prefix_misses = 0
        self._warned_prefix_miss = False
        if prefix_tokens:
            pfx = list(prefix_tokens)
            if len(pfx) >= max_len:
                raise ValueError(
                    f"prefix of {len(pfx)} tokens leaves no room within "
                    f"max_len={max_len}")
            tmp = engine.init_cache(cfg, 1, len(pfx))
            _, tmp = engine.prefill(
                self.params, jnp.asarray([pfx], jnp.int32), cfg, tmp)
            self._prefix = pfx
            self._prefix_kv = {"k": tmp.k, "v": tmp.v}
            if tmp.k_scale is not None:
                self._prefix_kv["k_scale"] = tmp.k_scale
                self._prefix_kv["v_scale"] = tmp.v_scale
            # remainder bucket list is a constant; precompute for the
            # per-request predicate on the scheduler hot path
            rcap = min(max_len - len(pfx), prefix_remainder_cap)
            self._rem_buckets = ([b for b in self.prompt_buckets
                                  if b < rcap] + [rcap])
        self.tokens_emitted = 0  # lifetime emitted tokens (bench/metrics)
        # request-lifecycle telemetry: histograms + counters observed at
        # host moments the scheduler already owns (no extra syncs); the
        # snapshot is the /metrics + /stats source of truth
        self.metrics = metrics if metrics is not None else ServingMetrics()
        self.metrics.registry.add_collector(self._collect_metrics)
        self.tracer = _StepTracer()  # /debug/trace on-demand profiling
        # iteration-phase profiler (inference/iteration_profile.py):
        # sweep/admission/build/device/commit/epilogue clock marks at
        # host moments the scheduler already crosses — zero extra
        # dispatches/syncs. The contiguous server has no flight
        # recorder, so phases feed only the per-phase histograms
        # (which is also where /stats' `iteration_profile` summary and
        # host_gap_frac come from). None (disabled) short-circuits
        # every guarded call site.
        from cloud_server_tpu.inference.iteration_profile import (
            register_phase_hists, resolve_profiler)
        self._profiler = resolve_profiler(iteration_profile,
                                          infer_cfg.iteration_profile)
        self._phase_hists = ({} if self._profiler is None else
                             register_phase_hists(self.metrics.registry))
        # idle-vs-dead disambiguation (see the paged server): an idle
        # scheduler keeps incrementing idle_iterations while
        # last_busy_ts ages; a dead one freezes both
        self.idle_iterations = 0
        self.last_busy_ts = 0.0
        self._iter_busy = False  # scheduler-thread scratch (under
        #                          _step_lock): did this step dispatch?
        # backpressure: submit() past this bound raises QueueFullError
        # (HTTP 429); None = unbounded (library use, trusted callers)
        self.max_pending = max_pending
        # multi-tenant QoS (inference/qos.py): `qos` may be a ready
        # TenantRegistry, a config dict / JSON string / file path, or
        # None (falls back to InferConfig.qos_config). None disables
        # QoS: every guarded call site below short-circuits and the
        # scheduler is byte-identical to the pre-QoS server. Imported
        # lazily — qos.py imports QueueFullError from this module.
        from cloud_server_tpu.inference.qos import resolve_registry
        self.qos = resolve_registry(qos, infer_cfg.qos_config)
        # per-request distributed tracing + per-class SLO tracking
        # (inference/request_trace.py, inference/slo.py): both None
        # unless configured — every guarded call site short-circuits
        # and the scheduler is byte-identical to the pre-trace build
        from cloud_server_tpu.inference.request_trace import (
            resolve_recorder)
        from cloud_server_tpu.inference.slo import resolve_slo
        self.trace_recorder = resolve_recorder(
            tracing, infer_cfg.trace_sample_rate,
            capacity=infer_cfg.trace_capacity,
            tail_capacity=infer_cfg.trace_tail_capacity)
        self.slo = resolve_slo(slo, infer_cfg.slo_config)
        if self.slo is not None:
            self.metrics.slo = self.slo
        # anomaly watchdog (inference/anomaly.py): None unless
        # configured — every guarded call site short-circuits and the
        # scheduler is byte-identical to the pre-watchdog build. The
        # contiguous server feeds the per-finish rules plus a thin
        # per-step signal (no flight recorder here); bundle
        # auto-capture shares the paged server's contract.
        from cloud_server_tpu.inference.anomaly import resolve_anomaly
        self._anomaly = resolve_anomaly(anomaly, infer_cfg.anomaly_config)
        if self._anomaly is not None:
            self._anomaly.bind_slo(self.slo)
        self._bundle_on_anomaly = bool(infer_cfg.bundle_on_anomaly)
        self._bundles: collections.deque = collections.deque(maxlen=8)
        self._bundles_captured = 0
        # deterministic fault injection (inference/faults.py): None
        # unless configured — every guarded call site short-circuits,
        # so the scheduler is byte-identical to the pre-fault build
        # (the dispatch-count regression test pins it). The contiguous
        # server arms submit_reject / dispatch / iteration_stall;
        # wedge and alloc_famine are paged-scheduler shapes.
        from cloud_server_tpu.inference.faults import resolve_fault_plan
        self._faults = resolve_fault_plan(faults, infer_cfg.fault_plan)
        self._draining = False
        self._slots: list[Request | None] = [None] * max_slots
        self._pending: collections.deque[Request] = collections.deque()
        self._lock = threading.Lock()
        # submit notifies this condition (same mutex as _lock) so an
        # idle serve_forever parks in a bounded wait instead of
        # busy-polling (see the paged server's twin)
        self._work = threading.Condition(self._lock)
        # Async launch-ahead decode (`InferConfig.overlap` / overlap=,
        # default on): the decode chunk launched at the END of a step
        # commits at the START of the next one, so the sweep, the
        # admission burst (its own prefill dispatch included), and the
        # step epilogue all run while the device decodes. The launch
        # always happens AFTER the commit against the fully-committed
        # ledger — the contiguous server's simpler shape of the paged
        # server's double-buffered scheduler (no planned frame, no
        # patching). overlap=False keeps the sequential loop
        # byte-identical.
        ov = infer_cfg.overlap if overlap is None else bool(overlap)
        self.overlap = bool(ov)
        self._overlap_enabled = self.overlap
        # (decode output futures, _slots snapshot at launch) — the
        # snapshot identity-guards the commit: a slot freed and
        # re-admitted while the chunk was in flight must not receive
        # the old occupant's tokens
        self._inflight: tuple | None = None
        self._iter_overlapped = False  # scheduler-thread scratch
        # Serialises whole scheduler iterations: step() mutates self.state
        # through buffer-donating jits, so two concurrent step() calls
        # (e.g. run_until_idle() on an already start()ed server) would hand
        # one thread a buffer the other just donated.
        self._step_lock = threading.Lock()
        self._rng = jax.random.key(seed)
        # host RNG: default per-request seeds for unseeded requests
        self._host_rng = np.random.default_rng(seed)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- client API ---------------------------------------------------------

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: int | None = None,
               stream: Callable[[int], None] | None = None,
               sampling: SamplingParams | None = None,
               tenant: str | None = None,
               trace_ctx: tuple | None = None,
               deadline_s: float | None = None,
               fail_handler=None) -> Request:
        if self._stop.is_set():
            # stop() was called or serve_forever died on a fatal error —
            # accepting now would enqueue work nothing will ever drain and
            # hang the caller's result() forever.
            raise RuntimeError("server is stopped; not accepting requests")
        if self._faults is not None:
            self._faults.check("submit_reject")
        if deadline_s is not None and not (
                math.isfinite(deadline_s) and deadline_s > 0):
            # `not (x > 0)` rather than `x <= 0`: NaN compares False
            # BOTH ways and would otherwise slip through as a silent
            # never-expiring deadline
            raise ValueError("deadline_s must be a finite positive "
                             "number of seconds")
        if sampling is not None and sampling.regex is not None:
            raise ValueError(
                "regex-constrained decoding is served by the paged "
                "server (PagedInferenceServer), not the contiguous one")
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        _bucket(len(prompt), self.prompt_buckets)  # raises if too long
        max_new = (self.infer_cfg.max_decode_len if max_new_tokens is None
                   else max_new_tokens)
        max_new = min(max_new, self.max_len - len(prompt))
        if max_new <= 0:
            raise ValueError(
                f"prompt of {len(prompt)} tokens leaves no room to decode "
                f"within max_len={self.max_len}")
        if self.qos is not None:
            tenant = self.qos.resolve(tenant)
        else:
            # no registry = no frozen tenant set to bound cardinality:
            # a caller-supplied string must not mint per-tenant labeled
            # metric series (observe_emit labels by req.tenant)
            tenant = None
        req = Request(prompt=list(prompt), max_new_tokens=max_new,
                      stream=stream, sampling=sampling, tenant=tenant,
                      seed_used=resolve_seed(sampling, self._host_rng,
                                             self._lock),
                      submit_time=time.perf_counter())
        if deadline_s is None and self.qos is not None:
            # per-QoS-class default deadline (None when the tenant's
            # class declares none)
            deadline_s = self.qos.default_deadline(tenant)
        if deadline_s is not None:
            req.deadline = req.submit_time + float(deadline_s)
        if self.slo is not None:
            # class mapping: the tenant's QoS priority class; plain
            # "default" without a registry
            req.slo_class = (self.qos.priority_class(tenant)
                             if self.qos is not None else None)
        # the router's failover hook rides in THROUGH submit (not
        # installed after it returns): once the request is in the
        # pending queue any scheduler crash may complete it, and a
        # hook landing late would miss its own failure
        req._fail_handler = fail_handler
        req._on_cancel = self._handle_cancel
        with self._lock:
            # under the lock: drain() flips _draining under the same
            # lock, so a submit either lands before drain observes the
            # queue or is rejected — never appended-then-abandoned
            if self._draining:
                raise RuntimeError(
                    "server is draining; not accepting requests")
            if (self.max_pending is not None
                    and len(self._pending) >= self.max_pending):
                raise QueueFullError(
                    f"pending queue is full ({self.max_pending} "
                    "requests); retry later")
            if self.qos is not None:
                # per-tenant backpressure AFTER the global bound: a
                # TenantQueueFullError here leaves no trace — the
                # tenant's pending count only advances on success,
                # atomically with the append below
                self.qos.gate_submit(tenant, len(prompt))
            # telemetry BEFORE the append: once the request is in the
            # queue the scheduler thread may admit (even finish) it, and
            # the timeline must stay in lifecycle order. The trace
            # opens here too — AFTER every rejection path above, so a
            # refused submit can never leak into the recorder's live
            # set, and before the append, so the scheduler cannot
            # finish the request ahead of its trace existing.
            if self.trace_recorder is not None:
                tr = self.trace_recorder.begin(req, trace_ctx)
                if tr is not None and tenant is not None:
                    tr.annotate(tenant=tenant)
            req.record_event("submit", req.submit_time)
            self.metrics.observe_submit(req)
            self._pending.append(req)
            # wake an idle scheduler thread parked on the bounded
            # condition wait (serve_forever)
            self._work.notify()
        return req

    def _handle_cancel(self, req: Request) -> None:
        """Client-thread half of Request.cancel(): pending requests
        finish immediately; an active slot is reaped by the sweep at
        the start of the next step()."""
        with self._lock:
            try:
                self._pending.remove(req)
            except ValueError:
                return  # active: the step sweep owns the teardown
            if self.qos is not None:
                self.qos.on_pending_removed(req.tenant)
        req.finish_reason = "cancelled"
        self._complete(req)

    def _complete(self, req: Request) -> None:
        """Terminal bookkeeping for any request leaving the server:
        observe lifecycle metrics (finish reason, e2e latency), then
        unblock waiters. Every path that ends a request goes through
        here so the telemetry can never miss a terminal state.

        Failure interception: a request completing with an "error:"
        reason is offered to its `_fail_handler` (installed by the
        ReplicatedRouter at submit) AFTER the telemetry — the failure
        really happened here — but BEFORE `_done`: a True return means
        a failover retry on another replica now owns completion, so
        waiters stay blocked until the retry finishes and mirrors its
        outcome back."""
        now = self.metrics.observe_finish(req)
        if self._anomaly is not None:
            ttft = (req.emit_times[0] - req.submit_time
                    if req.emit_times and req.submit_time is not None
                    else None)
            itl = (None if len(req.emit_times) < 2 else
                   (req.emit_times[-1] - req.emit_times[0])
                   / (len(req.emit_times) - 1))
            fired = self._anomaly.observe_request(
                now=now, ttft_s=ttft, itl_s=itl,
                finish_reason=req.finish_reason)
            if fired:
                self._on_anomaly(fired)
        if self.trace_recorder is not None and (
                req.trace is not None or req.tail_trace is not None):
            slo_violated = False
            if req.trace is None and self.slo is not None:
                e2e = (None if req.submit_time is None
                       else now - req.submit_time)
                ttft = (req.emit_times[0] - req.submit_time
                        if req.emit_times and req.submit_time is not None
                        else None)
                slo_violated = (
                    (e2e is not None and self.slo.exceeds_target(
                        req.slo_class, "e2e", e2e))
                    or (ttft is not None and self.slo.exceeds_target(
                        req.slo_class, "ttft", ttft)))
            in_anomaly = (self._anomaly is not None
                          and req.trace is None
                          and self._anomaly.active_count(now) > 0)
            self.trace_recorder.finish(req, slo_violated=slo_violated,
                                       in_anomaly=in_anomaly)
        h = req._fail_handler
        if (h is not None and req.finish_reason is not None
                and req.finish_reason.startswith("error") and h(req)):
            return
        req._done.set()
        cb = req._on_done
        if cb is not None:
            cb(req)

    def _sweep_cancelled(self) -> None:
        now = None
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            if req._cancel.is_set():
                req.finish_reason = "cancelled"
                self._finish(slot, req)
                continue
            if req.deadline is not None:
                if now is None:  # lazily: zero reads with no deadlines
                    now = time.perf_counter()
                if now > req.deadline:
                    req.finish_reason = "deadline"
                    self._finish(slot, req)
        # expired PENDING requests: reaped here too, so a deadline is
        # honored even if the request never reaches a slot
        with self._lock:
            expired = []
            if any(r.deadline is not None for r in self._pending):
                if now is None:
                    now = time.perf_counter()
                keep = collections.deque()
                for r in self._pending:
                    if r.deadline is not None and now > r.deadline:
                        expired.append(r)
                    else:
                        keep.append(r)
                self._pending = keep
            for r in expired:
                if self.qos is not None:
                    self.qos.on_pending_removed(r.tenant)
        for r in expired:
            r.finish_reason = "deadline"
            self._complete(r)

    def generate(self, prompts: Sequence[Sequence[int]], *,
                 max_new_tokens: int | None = None) -> list[list[int]]:
        """Synchronous convenience: submit all, drive to completion."""
        reqs = [self.submit(p, max_new_tokens=max_new_tokens)
                for p in prompts]
        self.run_until_idle()
        return [r.tokens for r in reqs]

    # -- scheduler ----------------------------------------------------------

    def _next_rng(self) -> jax.Array:
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _emit(self, req: Request, token: int,
              logprob: float | None = None) -> bool:
        """Record one generated token; True if the request just finished."""
        n0 = len(req.emit_times)
        done = emit_token(req, token, logprob, self.infer_cfg)
        # count every token the model computed and the stream accepted —
        # a stop-sequence match truncates the request's token list but
        # those tokens were still generated (throughput accounting)
        if not (done and req.finish_reason == "eos"):
            self.tokens_emitted += 1
            if self.qos is not None:
                self.qos.charge_generated(req.tenant)
        if len(req.emit_times) > n0:  # a stop match truncates instead
            self.metrics.observe_emit(req)
        return done

    def _finish(self, slot: int, req: Request) -> None:
        self._slots[slot] = None
        self.state = _deactivate(self.state, jnp.int32(slot))
        self._complete(req)

    def _admit_pending(self) -> None:
        """Admit every admissible pending request in ONE batched prefill.

        A burst of K pending requests costs one `_admit_batch` dispatch and
        one device_get (the first tokens), so active decode slots stall for
        a single prefill round-trip rather than K of them. The group's
        padded length is the bucket of its longest prompt and its row count
        is padded to a power of two, bounding compilations to
        O(len(prompt_buckets) * log2(max_slots)).
        """
        with self._lock:
            if not self._pending:
                return
            free = [i for i, r in enumerate(self._slots) if r is None]
            group: list[tuple[int, Request]] = []
            while self._pending and len(group) < len(free):
                if self.qos is not None:
                    # deficit-round-robin over tenants (FIFO within a
                    # tenant; degenerates to plain FIFO with a single
                    # tenant) — the fair-share admission policy
                    idx = self.qos.next_admission_index(self._pending)
                    req = self._pending[idx]
                    del self._pending[idx]
                    self.qos.charge_admission(req.tenant,
                                              len(req.prompt))
                    self.qos.on_pending_removed(req.tenant)
                else:
                    req = self._pending.popleft()
                slot = free[len(group)]
                self._slots[slot] = req
                group.append((slot, req))
        if not group:
            return
        self._iter_busy = True
        if self._profiler is not None:
            # QoS/DRR group selection under the lock; the burst's
            # padding/dispatch below stamps build/device/commit. The
            # mark's timestamp doubles as the admit moment below — one
            # clock read serves both
            now = self._profiler.mark("admission")
        else:
            now = time.perf_counter()  # one read per admission burst
        for _, req in group:
            self.metrics.observe_admit(req, now)
        prefixed, plain = [], []
        for gr in group:  # one predicate evaluation per request
            (prefixed if self._use_prefix(gr[1]) else plain).append(gr)
        if plain:
            self._admit_group_plain(plain)
        if prefixed:
            self._admit_group_prefixed(prefixed)

    def _use_prefix(self, req: Request) -> bool:
        """Fast-path predicate; also tracks hit/miss counters. A miss is
        NOT necessarily an error (mixed traffic is expected) but a server
        that never hits usually means the prefix isn't a token-level
        prefix of the prompts — e.g. a BPE tokenizer merging across the
        prefix/remainder text boundary — so the first miss warns once.
        """
        pfx = self._prefix
        if pfx is None:
            return False
        ok = (len(req.prompt) > len(pfx)
              # cap: verify_step's dense attention is fine for moderate
              # remainders but would materialise O(R x (P0+R)) scores
              # for huge ones — the plain (flash-capable) prefill wins
              and len(req.prompt) - len(pfx) <= self._rem_buckets[-1]
              and req.prompt[:len(pfx)] == pfx)
        if ok:
            self.prefix_hits += 1
        else:
            self.prefix_misses += 1
            if not self._warned_prefix_miss:
                self._warned_prefix_miss = True
                import sys
                print("[server] request did not match the cached prefix "
                      "(token-level comparison) — with a BPE tokenizer, "
                      "text that merges across the prefix boundary never "
                      "matches; check prefix_hits/prefix_misses",
                      file=sys.stderr)
        return ok

    def _pad_group(self, group, token_rows, buckets):
        """Padded (token rows, true_lens, slot indices) numpy arrays for
        an admission burst: width = the bucket of the longest entry, row
        count = next power of two; rows filled, padding rows target
        slot == max_slots (out of range -> dropped by the scatters)."""
        pb = _bucket(max(len(t) for t in token_rows), buckets)
        gpad = 1
        while gpad < len(group):
            gpad *= 2
        rows = np.full((gpad, pb), self.infer_cfg.pad_token_id, np.int32)
        true_lens = np.ones((gpad,), np.int32)
        slots = np.full((gpad,), self.max_slots, np.int32)
        for i, toks_i in enumerate(token_rows):
            rows[i, :len(toks_i)] = toks_i
            true_lens[i] = len(toks_i)
            slots[i] = group[i][0]
        return rows, true_lens, slots

    def _ensure_penalty_state(self, group) -> None:
        """Materialize the (B, V) penalty buffers on the first admission
        that needs them (one-time recompile of the dispatches; slots
        admitted before materialization carry neutral penalties, for
        which the buffers are read-irrelevant)."""
        if self.state.prompt_mask is not None or not any(
                req.sampling is not None
                and req.sampling.needs_penalty_state()
                for _, req in group):
            return
        s = self.state
        v = self.cfg.vocab_size
        self.state = SlotState(
            k=s.k, v=s.v, length=s.length, last_token=s.last_token,
            active=s.active, k_scale=s.k_scale, v_scale=s.v_scale,
            samp=s.samp,
            prompt_mask=jnp.zeros((self.max_slots, v), bool),
            out_counts=jnp.zeros((self.max_slots, v), jnp.int32))

    def _group_rows(self, group, gpad: int) -> tuple[SamplingRows, bool]:
        """SamplingRows for an admission burst, padded to `gpad` rows
        (the row count `_pad_group` chose — the jitted admission needs
        the two paddings in lockstep) + whether any member needs the
        device rows path. Padding rows are zeros (their slot index drops
        every scatter anyway)."""
        params_list = [req.sampling for _, req in group]
        seeds = [req.seed_used for _, req in group]
        plens = [len(req.prompt) for _, req in group]
        params_list += [None] * (gpad - len(group))
        seeds += [0] * (gpad - len(group))
        plens += [0] * (gpad - len(group))
        rows = make_rows(params_list, self.infer_cfg, seeds,
                         prompt_lens=plens)
        use = any(sp is not None and sp.needs_device_rows(self.infer_cfg)
                  for sp in params_list)
        bias = any(sp is not None and bool(sp.logit_bias)
                   for sp in params_list)
        return rows, use, bias

    def _rows_mode(self) -> tuple[bool, bool]:
        """(use_rows, use_bias): whether any ACTIVE request needs
        per-request device sampling / logit_bias — such a request's
        whole lifetime then runs rows-mode dispatches, which is what
        keeps its penalty counts advancing."""
        live = [r.sampling for r in self._slots
                if r is not None and r.sampling is not None]
        return (any(sp.needs_device_rows(self.infer_cfg) for sp in live),
                any(bool(sp.logit_bias) for sp in live))

    def _admit_group(self, group, token_rows, buckets, run_fn) -> None:
        """Shared burst plumbing: pad, dispatch one batched admission,
        emit first tokens."""
        rows, true_lens, slots = self._pad_group(group, token_rows,
                                                 buckets)
        self._ensure_penalty_state(group)
        samp_rows, use_rows, use_bias = self._group_rows(
            group, rows.shape[0])
        prof = self._profiler
        if prof is not None:
            prof.mark("build")
        self.state, toks, lps = run_fn(
            jnp.asarray(rows), jnp.asarray(true_lens), jnp.asarray(slots),
            jax.tree.map(jnp.asarray, samp_rows), use_rows, use_bias)
        toks, lps = jax.device_get((toks, lps))
        if prof is not None:
            prof.mark("device")
        for i, (slot, req) in enumerate(group):
            if self._emit(req, int(toks[i]), float(lps[i])):
                self._finish(slot, req)
        if prof is not None:
            prof.mark("commit")

    def _admit_group_plain(self, group) -> None:
        def run(rows, tl, sl, samp, use_rows, use_bias):
            return _admit_batch(self.params, self.state, rows, tl, sl,
                                self._next_rng(), samp, cfg=self.cfg,
                                infer_cfg=self.infer_cfg,
                                use_rows=use_rows, use_bias=use_bias)

        self._admit_group(group, [r.prompt for _, r in group],
                          self.prompt_buckets, run)

    def _admit_group_prefixed(self, group) -> None:
        p0 = len(self._prefix)

        def run(rows, tl, sl, samp, use_rows, use_bias):
            return _admit_batch_prefixed(
                self.params, self.state, self._prefix_kv, rows, tl, sl,
                self._next_rng(), samp,
                jnp.asarray(self._prefix, jnp.int32), cfg=self.cfg,
                infer_cfg=self.infer_cfg, use_rows=use_rows,
                use_bias=use_bias)

        self._admit_group(group, [req.prompt[p0:] for _, req in group],
                          self._rem_buckets, run)

    @property
    def num_active(self) -> int:
        return sum(r is not None for r in self._slots)

    @property
    def num_pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def _chunk_len(self) -> int:
        """Decode steps to run this iteration: bounded by decode_chunk and
        by the tightest remaining token budget among active requests (so a
        chunk can never decode past a request's max_new_tokens, which also
        bounds its cache length — submit() guarantees prompt + max_new <=
        max_len). Rounded down to a power of two to bound compilations."""
        remaining = min(r.max_new_tokens - len(r.tokens)
                        for r in self._slots if r is not None)
        n = min(self.decode_chunk, max(1, remaining))
        p = 1
        while p * 2 <= n:
            p *= 2
        return p

    def step(self) -> int:
        """One scheduler iteration; returns number of active slots.

        Thread-safe: concurrent callers serialise on an internal lock.
        """
        with self._step_lock:
            self.tracer.step_start()
            prof = self._profiler
            try:
                if self._faults is not None:
                    # injected host stall: the scheduler thread pays
                    # it exactly like a slow host/device round would
                    self._faults.maybe_stall()
                if prof is not None:
                    prof.begin()
                self._iter_busy = False
                self._iter_overlapped = False
                n_active = self._step_locked()
                if self._iter_busy:
                    if prof is not None:
                        # epilogue = the post-commit tail of the step;
                        # phases feed the rolling histograms (the
                        # contiguous server's only phase sink). An
                        # overlapped step's sweep/admission/build ran
                        # under the in-flight decode — fold them into
                        # the `overlap` series (see iteration_profile)
                        prof.mark("epilogue")
                        hists = self._phase_hists
                        phases = prof.phases_ms()
                        if self._iter_overlapped:
                            hists["overlap"].observe(
                                sum(phases.get(p, 0.0)
                                    for p in OVERLAP_PHASES))
                            for p, v in phases.items():
                                if p not in OVERLAP_PHASES:
                                    hists[p].observe(v)
                        else:
                            for p, v in phases.items():
                                hists[p].observe(v)
                    self.last_busy_ts = time.time()
                    if self._anomaly is not None:
                        # thin per-step feed (no flight recorder here):
                        # one clock read, matching the brownout
                        # detector's per-observe budget
                        with self._lock:
                            pending = len(self._pending)
                        fired = self._anomaly.observe_iteration(
                            now=time.perf_counter(), pending=pending)
                        if fired:
                            self._on_anomaly(fired)
                else:
                    self.idle_iterations += 1
                return n_active
            finally:
                self.tracer.step_end()

    def _step_locked(self) -> int:
        if self._overlap_enabled:
            return self._step_locked_overlap()
        prof = self._profiler
        self._sweep_cancelled()
        if prof is not None:
            prof.mark("sweep")
        self._admit_pending()
        if self.num_active == 0:
            return 0
        self._iter_busy = True
        if self._faults is not None:
            # injected dispatch failure: raises before any device work,
            # crashing this iteration the way a poisoned program would
            # (serve_forever catches, _fail_all unblocks every waiter)
            self._faults.check("dispatch")
        n = self._chunk_len()
        use_rows, use_bias = self._rows_mode()
        if prof is not None:
            # decode planning; the dispatch statements below (arg
            # transfer + launch + the sanctioned device_get) are the
            # device phase — the contiguous decode stages no host
            # arrays, so its build phase is empty by construction
            prof.mark("admission")
        if n == 1:
            self.state, out = _decode(
                self.params, self.state, self._next_rng(),
                cfg=self.cfg, infer_cfg=self.infer_cfg,
                use_rows=use_rows, use_bias=use_bias)
            toks, lps = jax.device_get(out)
            chunk = np.asarray(toks)[None]       # (1, B)
            lchunk = np.asarray(lps)[None]
        else:
            self.state, out = _decode_chunk(
                self.params, self.state, self._next_rng(),
                cfg=self.cfg, infer_cfg=self.infer_cfg, n_steps=n,
                use_rows=use_rows, use_bias=use_bias)
            toks, lps = jax.device_get(out)
            chunk = np.asarray(toks)             # (n, B)
            lchunk = np.asarray(lps)
        if prof is not None:
            prof.mark("device")
        for t in range(chunk.shape[0]):
            for slot, req in enumerate(self._slots):
                if req is not None and self._emit(
                        req, int(chunk[t, slot]),
                        float(lchunk[t, slot])):
                    self._finish(slot, req)
        if prof is not None:
            prof.mark("commit")
        return self.num_active

    def _launch_decode(self, use_rows: bool, use_bias: bool):
        """Launch one decode chunk asynchronously (no device_get) —
        the ONE dispatch site the overlap steady-state launch and the
        pipeline-fill prime share, so a signature change can never
        desync them. The round count comes from `_chunk_len` HERE
        (the audited pow2 planner — DD4's boundedness requires the
        static `n_steps` to be derived inside the dispatching
        function, not passed through an unbounded parameter). Returns
        the output futures."""
        n = self._chunk_len()
        if n == 1:
            self.state, out = _decode(
                self.params, self.state, self._next_rng(),
                cfg=self.cfg, infer_cfg=self.infer_cfg,
                use_rows=use_rows, use_bias=use_bias)
        else:
            self.state, out = _decode_chunk(
                self.params, self.state, self._next_rng(),
                cfg=self.cfg, infer_cfg=self.infer_cfg, n_steps=n,
                use_rows=use_rows, use_bias=use_bias)
        return out

    def _commit_decode_chunk(self, out, slots, prof) -> None:
        """Sync one decode chunk and emit its tokens against `slots`
        (a _slots snapshot for a launch-ahead commit, the live list on
        the pipeline-fill path) with the per-row identity guard — THE
        one commit-emit block both overlap paths share, and the
        sanctioned per-iteration host sync of the pipelined
        contiguous loop (dispatch-discipline DD2)."""
        toks, lps = jax.device_get(out)
        if prof is not None:
            prof.mark("device")
        chunk, lchunk = np.asarray(toks), np.asarray(lps)
        if chunk.ndim == 1:
            chunk, lchunk = chunk[None], lchunk[None]
        for t in range(chunk.shape[0]):
            for slot, req in enumerate(slots):
                if req is not None and self._slots[slot] is req \
                        and self._emit(req, int(chunk[t, slot]),
                                       float(lchunk[t, slot])):
                    self._finish(slot, req)
        if prof is not None:
            prof.mark("commit")

    def _step_locked_overlap(self) -> int:
        """Pipelined iteration (overlap on): commit the decode chunk
        launched at the END of the previous step, then launch the next
        chunk and return with it in flight — the sweep, the admission
        burst (its own prefill dispatch and sanctioned sync included),
        and the next step's epilogue all run while the device decodes.

        Unlike the paged server's planner, nothing here reads stale
        state: the launch always follows the commit, so the chunk
        length and the slot snapshot see the fully-committed ledger.
        The snapshot identity-guards the commit (a slot freed by the
        sweep and re-admitted mid-flight must not receive the old
        occupant's tokens; its device row is overwritten by the
        admission program, which chains after the in-flight decode).
        With nothing in flight (cold start / post-idle) the step runs
        the sequential dispatch-sync-commit, then PRIMES the pipeline
        with a launch-ahead before returning — so per-step emission
        counts match the sequential loop exactly."""
        prof = self._profiler
        self._sweep_cancelled()
        if prof is not None:
            prof.mark("sweep")
        self._admit_pending()
        if prof is not None:
            # close the admission window HERE: with a chunk in flight
            # the commit's device mark comes next, and an
            # admission-less scan must not leak into `device` (the
            # burst's own build/device/commit marks accumulated above)
            prof.mark("admission")
        committed = False
        if self._inflight is not None:
            self._iter_busy = True
            self._iter_overlapped = True
            out, snap = self._inflight
            self._inflight = None
            self._commit_decode_chunk(out, snap, prof)
            committed = True
        if self.num_active == 0:
            return 0
        self._iter_busy = True
        if self._faults is not None:
            # injected dispatch failure: raises before any device work
            # (with a chunk possibly in flight the commit above already
            # ran, so no synced tokens are ever lost to the injection)
            # analysis: allow[lifecycle-discipline] deliberate raise point: a dispatch fault fails the whole step and _fail_all tears every slot down, so the _iter_busy/_inflight pair is never read torn
            self._faults.check("dispatch")
        use_rows, use_bias = self._rows_mode()
        if prof is not None:
            prof.mark("admission")
        out = self._launch_decode(use_rows, use_bias)
        if committed:
            # steady state: leave the chunk in flight (launch-ahead)
            self._inflight = (out, list(self._slots))
            if prof is not None:
                prof.mark("launch")
            return self.num_active
        # pipeline fill: sequential commit of the chunk just launched
        self._commit_decode_chunk(out, list(self._slots), prof)
        if self.num_active:
            # prime: the next chunk overlaps the NEXT step's host work
            # (its injected-fault site is the NEXT step's check — one
            # check per step, matching the sequential hit pacing)
            use_rows, use_bias = self._rows_mode()
            out = self._launch_decode(use_rows, use_bias)
            self._inflight = (out, list(self._slots))
            if prof is not None:
                prof.mark("launch")
        return self.num_active

    def _fail_all(self, exc: BaseException) -> None:
        """Unblock every in-flight and pending request after a fatal
        scheduler error (otherwise result() waiters hang forever)."""
        # drop any launched-but-uncommitted decode chunk's futures:
        # their tokens belong to requests failed below
        self._inflight = None
        with self._lock:
            pending, self._pending = list(self._pending), collections.deque()
        for slot, req in enumerate(self._slots):
            if req is not None:
                self._slots[slot] = None
                req.finish_reason = f"error: {exc!r}"
                self._complete(req)
        for req in pending:
            if self.qos is not None:
                self.qos.on_pending_removed(req.tenant)
            req.finish_reason = f"error: {exc!r}"
            self._complete(req)

    # -- observability ------------------------------------------------------

    def _collect_metrics(self) -> None:
        """Scrape-path mirror of host scheduler state into the registry
        (occupancy gauges + lifetime counters the server already keeps)."""
        reg = self.metrics.registry
        reg.gauge("active_slots",
                  "Requests currently decoding").set(self.num_active)
        reg.gauge("pending_requests",
                  "Queued requests awaiting admission").set(
                      self.num_pending)
        reg.counter("tokens_emitted_total",
                    "Lifetime generated tokens").set_total(
                        self.tokens_emitted)
        # idle-vs-dead disambiguation (mirrors the paged server)
        reg.counter("idle_iterations_total",
                    "step() calls that dispatched nothing").set_total(
                        self.idle_iterations)
        reg.gauge("last_busy_ts",
                  "Unix time of the last busy iteration (0 until the "
                  "first)").set(self.last_busy_ts)
        from cloud_server_tpu.inference.faults import SITES
        fstats = (self._faults.stats() if self._faults is not None
                  else None)
        for site in SITES:
            reg.counter("faults_injected_total",
                        "Deliberately injected faults that fired, "
                        "per site (inference/faults.py; zero without "
                        "an armed FaultPlan)",
                        labels={"site": site}).set_total(
                            0 if fstats is None
                            else fstats["fired"][site])
        reg.counter("prefix_hits_total",
                    "Admissions served from the cached prefix"
                    ).set_total(self.prefix_hits)
        reg.counter("prefix_misses_total",
                    "Admissions that missed the cached prefix"
                    ).set_total(self.prefix_misses)
        if self.qos is not None:
            self.qos.mirror_metrics(reg)
        if self.slo is not None:
            self.slo.mirror_metrics(reg)
        # anomaly watchdog + tail retention: families registered
        # unconditionally (zeros) so the /metrics catalog is stable —
        # the faults_injected_total pattern
        from cloud_server_tpu.inference.anomaly import RULES
        astats = (self._anomaly.stats(events=0)
                  if self._anomaly is not None else None)
        for rule in RULES:
            reg.gauge("anomaly_active",
                      "1 while the watchdog rule's anomaly window is "
                      "open (inference/anomaly.py; zero without an "
                      "anomaly config)",
                      labels={"rule": rule}).set(
                          0.0 if astats is None
                          else float(rule in astats["active"]))
            reg.counter("anomalies_total",
                        "Watchdog rule activations (one per anomaly "
                        "window opened, per rule)",
                        labels={"rule": rule}).set_total(
                            0 if astats is None
                            else astats["fired_total"][rule])
        rec = self.trace_recorder
        tstats = (rec.tail_stats() if rec is not None
                  and rec.tail_capacity > 0 else None)
        reg.counter("trace_tail_retained_total",
                    "Head-unsampled finished requests whose span "
                    "trees the tail-retention predicate kept"
                    ).set_total(0 if tstats is None else
                                sum(tstats["retained_total"].values()))
        reg.counter("trace_tail_evicted_total",
                    "Tail-retained trees evicted from the bounded "
                    "tail ring").set_total(
                        0 if tstats is None
                        else tstats["evicted_total"])
        reg.counter("anomaly_bundles_total",
                    "Forensic debug bundles auto-captured on anomaly "
                    "activation (bundle_on_anomaly)").set_total(
                        self._bundles_captured)

    def metrics_snapshot(self) -> dict:
        """Mergeable snapshot of every registered metric (the /metrics
        and /stats source; ReplicatedRouter merges these across
        replicas)."""
        return self.metrics.registry.snapshot()

    def iteration_profile_stats(self) -> dict | None:
        """The /stats `iteration_profile` summary (see the paged
        server's docstring). None with profiling disabled."""
        from cloud_server_tpu.inference.iteration_profile import (
            profile_summary)
        return profile_summary(self.metrics_snapshot())

    @property
    def ready(self) -> bool:
        """Readiness (vs the liveness /healthz always reported): False
        while draining or stopped, so load balancers — and the
        ReplicatedRouter's placement — stop routing new work here
        while in-flight requests finish."""
        return not self._draining and not self._stop.is_set()

    def lookup_trace(self, request_id: str) -> dict | None:
        """Span tree for one sampled request id (live or retained),
        else None (unsampled, evicted, or tracing disabled)."""
        rec = self.trace_recorder
        return None if rec is None else rec.lookup(request_id)

    def trace_trees(self, n: int | None = None) -> list[dict]:
        """Span trees of the sampled ring + live requests (the
        /traces export source)."""
        rec = self.trace_recorder
        return [] if rec is None else rec.trees(n)

    def slo_report(self) -> dict | None:
        """Per-class SLO attainment + burn rates (the /slo source;
        ReplicatedRouter merges these across replicas). None when no
        SLO config is set."""
        return None if self.slo is None else self.slo.report()

    def fault_stats(self) -> dict | None:
        """Per-site injected-fault hit/fired counts (the /stats
        `faults` block); None with no FaultPlan. Scrape path only."""
        return None if self._faults is None else self._faults.stats()

    def overlap_stats(self) -> dict:
        """The /stats `overlap` block (see the paged server's twin):
        launch-ahead decode pipelining state. Scrape path only."""
        return {
            "enabled": self.overlap,
            "active": self._overlap_enabled,
            "inflight_depth": 0 if self._inflight is None else 1,
        }

    def request_trace(self, n_steps: int,
                      logdir: str | os.PathLike) -> None:
        """Arm the /debug/trace capture: the next `n_steps` scheduler
        iterations run inside utils.tracing.capture_trace(logdir)."""
        self.tracer.request(n_steps, logdir)

    def anomaly_stats(self) -> dict | None:
        """The /stats `anomaly` block (active windows, per-rule
        activation counts, the bounded event ring); None with no
        watchdog. Scrape path only."""
        return None if self._anomaly is None else self._anomaly.stats()

    def anomaly_events(self, n: int | None = None) -> list[dict]:
        """Watchdog event dicts for the Perfetto marker track; empty
        with no watchdog."""
        return ([] if self._anomaly is None
                else self._anomaly.events(n))

    def tail_trace_trees(self, n: int | None = None) -> list[dict]:
        """Span trees of the tail-retained ring (anomalous requests
        kept past head sampling); empty with tail retention off."""
        rec = self.trace_recorder
        return ([] if rec is None or rec.tail_capacity <= 0
                else rec.tail_trees(n))

    def tail_trace_stats(self) -> dict | None:
        """The /stats tail-retention block; None with tail retention
        off."""
        rec = self.trace_recorder
        return (None if rec is None or rec.tail_capacity <= 0
                else rec.tail_stats())

    def _on_anomaly(self, fired) -> None:
        """Activation-edge reactions (rare by construction): snapshot
        a forensic bundle into the bounded ring when
        `bundle_on_anomaly` is set, and arm the existing /debug/trace
        capture machinery when the watchdog config asks for one.
        Forensics must never take the scheduler down — arming races
        (a capture already running) and bundle failures are
        swallowed."""
        if self._bundle_on_anomaly:
            try:
                self._bundles.append(self.debug_bundle(
                    trigger="anomaly:" + ",".join(fired)))
                self._bundles_captured += 1
            except Exception:  # noqa: BLE001 — see docstring
                pass
        wd = self._anomaly
        if wd is not None and wd.capture_iters > 0 and wd.capture_dir:
            try:
                self.tracer.request(wd.capture_iters, wd.capture_dir)
            except ValueError:
                pass  # a capture is already armed/running

    def debug_bundle(self, n: int = 64, *,
                     trigger: str = "manual") -> dict:
        """One-shot forensic artifact (the GET /debug/bundle payload):
        everything an incident post-mortem would otherwise stitch
        from five endpoints — metrics, retained + tail span trees,
        SLO report, fault/anomaly state — as one JSON-ready dict.
        `n` bounds the ring exports. Scrape path only (auto-capture
        calls it once per activation edge, which is rare by the
        watchdog's hysteresis)."""
        return {
            "schema": "cloud_server.debug_bundle/v1",
            "trigger": trigger,
            "ts": time.time(),
            "anomaly": self.anomaly_stats(),
            "metrics": self.metrics_snapshot(),
            "profile": self.iteration_profile_stats(),
            "traces": self.trace_trees(n),
            "tail_traces": self.tail_trace_trees(n),
            "tail_retention": self.tail_trace_stats(),
            "slo": self.slo_report(),
            "faults": self.fault_stats(),
            "overlap": self.overlap_stats(),
        }

    def debug_bundles(self, n: int | None = None) -> list[dict]:
        """The bounded ring of auto-captured bundles (oldest first;
        `n` bounds from the newest end, n <= 0 means none)."""
        if n is not None and n <= 0:
            return []
        bundles = list(self._bundles)
        return bundles if n is None else bundles[-n:]

    def run_until_idle(self) -> None:
        while self.num_pending or self.num_active:
            self.step()

    # -- background serving -------------------------------------------------

    def serve_forever(self, idle_sleep_s: float = 0.05) -> None:
        while not self._stop.is_set():
            try:
                busy = self.step()
            except Exception as exc:  # noqa: BLE001 — must not hang clients
                import traceback
                traceback.print_exc()
                self._fail_all(exc)
                self._stop.set()
                return
            # cooperative yield after every busy step (see the paged
            # server's twin): stream-consumer threads must get a
            # drain window even when the pipelined syncs return
            # instantly
            if busy:
                time.sleep(0)
            if busy == 0 and self.num_pending == 0:
                # bounded condition wait, not a short sleep poll: idle
                # CPU iterations stay bounded while submit() wakes the
                # thread immediately (see the paged server's twin)
                with self._work:
                    if not self._pending and not self._stop.is_set():
                        self._work.wait(idle_sleep_s)

    def drain(self, timeout: float | None = None, *,
              _resume_on_timeout: bool = True) -> bool:
        """Graceful drain: refuse new submissions, let everything
        already accepted finish. Returns True once idle — and STAYS
        draining (quiesced): call resume() to accept again, or stop()
        to shut down. On timeout returns False and RESUMES accepting
        (the in-flight work keeps running; call stop() to actually shut
        down — it fails whatever is still live so no waiter hangs).
        Same contract as the paged server's, including the
        `_resume_on_timeout=False` internal latch stop(drain=True) uses
        so a timed-out drain cannot reopen submission in the window
        before _stop is set."""
        with self._lock:
            self._draining = True
        deadline = (None if timeout is None
                    else time.perf_counter() + timeout)
        while self.num_pending or self.num_active:
            if deadline is not None and time.perf_counter() > deadline:
                if _resume_on_timeout:
                    with self._lock:
                        self._draining = False
                return False
            if self._thread is None:
                self.step()
            else:
                time.sleep(0.002)
        return True

    def resume(self) -> None:
        """Clear a successful drain's quiesce: accept submissions again
        (no thread restart needed — the scheduler never stopped)."""
        with self._lock:
            self._draining = False

    def stop(self, drain: bool = False,
             timeout: float | None = None) -> None:
        if drain and not self._stop.is_set():
            # keep _draining latched across a timed-out drain (see the
            # paged server's stop() for why)
            self.drain(timeout, _resume_on_timeout=False)
        self._stop.set()
        with self._lock:
            # wake a scheduler thread parked on the idle wait
            self._work.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        if self.num_pending or self.num_active:
            # a timed-out (or skipped) drain left live requests behind:
            # nothing will ever step them now — unblock their waiters
            self._fail_all(RuntimeError(
                "server stopped before the request completed"))

    def start(self) -> "InferenceServer":
        self._stop.clear()
        self._draining = False  # a stopped-then-restarted server serves
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True, name="inference-server")
        self._thread.start()
        return self

"""Exact width-k beam search over the batch engine.

TPU-first: all k beams of all B prompts ride ONE (B*k)-row batched KV
cache. Each step is a single batched `decode_step` forward, a top-2k
candidate selection, and a batched cache-row gather (the beam reorder)
— no per-beam dispatches, static shapes throughout, the whole search
is one `lax.scan` inside one jit per (B, P, k, max_new) signature.

Selection follows the standard 2k-candidate scheme (t5x/flax lineage):
each step takes the top 2k of cum_logprob + log p(token) over the k*V
continuations; candidates ending in EOS retire into a per-prompt
finished pool (score length-normalised by `length_penalty`), the best
k non-EOS candidates continue. Live beams still running at max_new
merge into the pool at the end, so the search always returns k ranked
hypotheses.

Reference parity note: view-sonic/Cloud-Server @ v0 is an empty tree
(SURVEY.md); this subsystem is part of the re-scoped build inventory
(search-based decoding for the batch API).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from cloud_server_tpu.config import ModelConfig
from cloud_server_tpu.inference import engine

NEG_INF = -1e30


def _norm_score(cum: jnp.ndarray, length, length_penalty: float):
    """Length-normalised ranking score: cum_logprob / len**penalty.
    penalty 0 = raw sum (longer means worse); 1 = mean logprob."""
    return cum / jnp.maximum(length, 1).astype(jnp.float32) ** length_penalty


@functools.partial(jax.jit,
                   static_argnames=("cfg", "k", "max_new", "eos_token_id",
                                    "pad_token_id", "length_penalty",
                                    "max_len"))
def beam_search(params, prompt: jnp.ndarray, *, cfg: ModelConfig,
                k: int = 4, max_new: int = 16, eos_token_id: int = -1,
                pad_token_id: int = 0, length_penalty: float = 1.0,
                max_len: int | None = None,
                prompt_lengths: jnp.ndarray | None = None):
    """prompt: (B, P) int32 (right-padded; pass prompt_lengths when
    ragged). Returns (tokens (B, k, max_new) int32 padded past EOS,
    scores (B, k) f32), best-first per prompt."""
    b, p = prompt.shape
    if 2 * k > cfg.vocab_size:
        # the 2k-candidate selection needs 2k distinct continuations of
        # ONE live beam at t=0 (the other k-1 start at NEG_INF): with
        # 2k > V, lax.top_k would select dead-beam NEG_INF candidates
        # and return duplicate/garbage hypotheses with no error
        raise ValueError(
            f"beam width k={k} needs 2*k <= vocab_size "
            f"({cfg.vocab_size}); the top-2k candidate selection "
            "breaks for tiny vocabularies")
    max_len = max_len or (p + max_new)
    if max_len < p + max_new:
        raise ValueError(f"max_len={max_len} < prompt + max_new")
    cache = engine.init_cache(cfg, b, max_len)
    logits, cache = engine.prefill(params, prompt, cfg, cache,
                                   prompt_lengths)

    # tile the prompt cache k-fold: rows [i*k, (i+1)*k) are prompt i's
    # beams (a device-side repeat — the prompt is prefilled ONCE)
    def tile(x):
        return None if x is None else jnp.repeat(x, k, axis=1)

    cache = engine.KVCache(k=tile(cache.k), v=tile(cache.v),
                           length=jnp.repeat(cache.length, k),
                           k_scale=tile(cache.k_scale),
                           v_scale=tile(cache.v_scale))
    logits = jnp.repeat(logits, k, axis=0)  # (B*k, V)
    v = logits.shape[-1]

    # beam 0 is the only live hypothesis at t=0 (all beams are identical
    # until the first selection — duplicates would crowd out the search)
    cum0 = jnp.full((b, k), NEG_INF, jnp.float32).at[:, 0].set(0.0)
    seq0 = jnp.full((b, k, max_new), pad_token_id, jnp.int32)
    fin_t0 = jnp.full((b, k, max_new), pad_token_id, jnp.int32)
    fin_s0 = jnp.full((b, k), NEG_INF, jnp.float32)
    bidx = jnp.arange(b)[:, None]

    def step(carry, t):
        logits, cache, cum, seq, fin_t, fin_s = carry
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        cand = cum[:, :, None] + logp.reshape(b, k, v)  # (B, k, V)
        sc2k, idx2k = lax.top_k(cand.reshape(b, k * v), 2 * k)
        parent = idx2k // v          # (B, 2k)
        tok = idx2k % v              # (B, 2k)
        # candidate sequences: parent's history + the new token at t
        parent_seq = seq[bidx, parent]                     # (B, 2k, M)
        cand_seq = parent_seq.at[:, :, t].set(tok)
        is_eos = tok == eos_token_id

        # EOS candidates retire; the EOS token itself is NOT stored
        # (matching the servers' emit rule) but its position counts
        # toward the length normalisation (t+1)
        fin_cand = jnp.where(is_eos,
                             _norm_score(sc2k, t + 1, length_penalty),
                             NEG_INF)
        pool_s = jnp.concatenate([fin_s, fin_cand], axis=1)  # (B, 3k)
        pool_t = jnp.concatenate([fin_t, parent_seq], axis=1)  # (B,3k,M)
        fin_s, fin_idx = lax.top_k(pool_s, k)
        fin_t = pool_t[bidx, fin_idx]

        # live continuation: best k non-EOS candidates
        live_sc = jnp.where(is_eos, NEG_INF, sc2k)
        cum, live_idx = lax.top_k(live_sc, k)               # (B, k)
        new_parent = jnp.take_along_axis(parent, live_idx, axis=1)
        new_tok = jnp.take_along_axis(tok, live_idx, axis=1)
        seq = jnp.take_along_axis(
            cand_seq, live_idx[..., None], axis=1)

        # reorder the cache rows under the surviving beams
        flat_parent = (jnp.arange(b)[:, None] * k + new_parent).reshape(-1)
        cache2 = engine.KVCache(
            k=cache.k[:, flat_parent], v=cache.v[:, flat_parent],
            length=cache.length[flat_parent],
            k_scale=(None if cache.k_scale is None
                     else cache.k_scale[:, flat_parent]),
            v_scale=(None if cache.v_scale is None
                     else cache.v_scale[:, flat_parent]))
        logits, cache2 = engine.decode_step(params, new_tok.reshape(-1),
                                            cfg, cache2)
        return (logits, cache2, cum, seq, fin_t, fin_s), None

    (logits, _, cum, seq, fin_t, fin_s), _ = lax.scan(
        step, (logits, cache, cum0, seq0, fin_t0, fin_s0),
        jnp.arange(max_new))

    # live beams at the horizon join the pool, length-normalised at
    # max_new
    live_s = _norm_score(cum, max_new, length_penalty)
    pool_s = jnp.concatenate([fin_s, live_s], axis=1)
    pool_t = jnp.concatenate([fin_t, seq], axis=1)
    scores, order = lax.top_k(pool_s, k)
    tokens = pool_t[bidx, order]
    return tokens, scores

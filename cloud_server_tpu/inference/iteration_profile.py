"""Iteration-phase profiler: host-gap attribution for the scheduler
hot loop.

The flight recorder (PR 3) stamps every busy scheduler iteration with
one `duration_ms` — enough to see that an iteration was slow, not
enough to say WHERE the time went. Before the async double-buffered
scheduler (ROADMAP item 4) can claim to overlap host policy work with
device compute, the measurement layer must exist: per-phase
attribution of every iteration, so the host-gap the overlap will hide
is a measured number (`host_gap_frac`), not an inference from
end-to-end tok/s.

Phase taxonomy (one contiguous partition of the iteration, stamped at
boundaries the scheduler already crosses):

    sweep       cancelled-request reaping at the top of step()
    admission   QoS/DRR admission, token-budget planning, chain
                extension/preemption policy — the host DECIDING what
                to dispatch
    build       host array prep (numpy staging, padding, gathers) up
                to the jitted call
    device      the dispatch statement (arg device transfer + launch)
                through the one sanctioned `device_get` commit point —
                the only phase that waits on the accelerator
    commit      token emit / grammar / speculation bookkeeping on the
                synced results
    launch      (async scheduler only) the ledger patch + next-
                dispatch launch that follows the commit — the tail of
                the serialized critical path when overlap is on
    epilogue    flight-recorder / tracing / SLO bookkeeping at the end
                of the iteration

SEQUENTIAL iterations (overlap off, or nothing in flight):
`host_gap_frac` = (everything except `device`) / duration — the
fraction of each iteration the device sits idle while the host works.

OVERLAPPED iterations (the async double-buffered scheduler, ROADMAP
item 4 — now built): sweep / admission / build run WHILE the device
executes the previous iteration's program, so they are no longer
device-idle time. Those phases fold into `overlap_ms` (and the single
`overlap`-labeled histogram series), `device` becomes the RESIDUAL
wait after the overlapped host work, and `host_gap_frac` measures
only the serialized host tail (`commit` + `launch` + `epilogue`) —
the residual cost the overlap could not hide. The per-record identity
becomes `host_ms + device_wait_ms + overlap_ms == duration_ms`.

Design rules (the metrics layer's own):

  * **Stdlib only, zero device work.** The clock is
    `time.perf_counter`; a phase mark is one clock read and one dict
    add. The module is on the analysis hot-path lint roster AND the
    dispatch-discipline host-policy (jax-free) roster; the mixed
    scheduler's dispatch/sync-count regression test runs a
    profiling-enabled clone, and a bounded CONSTANT number of clock
    reads per mixed iteration is asserted by monkeypatching
    `perf_counter` (tests/test_iteration_profile.py).
  * **Same plumbing as every other signal.** Phases land in the
    flight record (`phases_ms` + derived `host_ms` /
    `device_wait_ms` / `host_gap_frac`), in rolling per-phase
    histograms (`cloud_server_iter_phase_ms`, labeled by phase,
    fleet-merged bucket-for-bucket through
    `ReplicatedRouter.metrics_snapshot()`), in `/stats`
    (`iteration_profile`: per-phase p50/p99 + `host_gap_frac`), and
    in a scheduler-timeline Perfetto export
    (`GET /debug/scheduler_trace?n=K`) cross-linked to the
    per-request span trees by the flight-recorder iteration index.
  * **Disable-able.** `InferConfig.iteration_profile` (default on) /
    the servers' `iteration_profile=` constructor argument; disabled
    servers keep the exact pre-profiler clock behavior (two
    perf_counter reads per busy iteration).

Timebase note: with profiling enabled, a busy iteration's
`duration_ms` spans the WHOLE iteration (sweep through epilogue), so
`host_ms + device_wait_ms == duration_ms` by construction; with it
disabled, `duration_ms` keeps its historical meaning (dispatch start
to epilogue). Flight records gain `t_start` (the iteration's
perf_counter start), which is what lets the scheduler timeline export
share a timebase with the request-trace export (`GET /traces`).
"""

from __future__ import annotations

from time import perf_counter

from cloud_server_tpu.utils.serving_metrics import histogram_percentile

# Canonical phase order — the contiguous partition of one iteration.
# `launch` only appears in overlapped iterations (async scheduler).
PHASES = ("sweep", "admission", "build", "device", "commit", "launch",
          "epilogue")

# Phases that run concurrently with the in-flight device program when
# the async double-buffered scheduler has a dispatch outstanding; they
# fold into the `overlap` histogram label and `overlap_ms`.
OVERLAP_PHASES = ("sweep", "admission", "build")

# Histogram label set: the fine-grained phases plus the folded
# `overlap` series overlapped iterations observe instead of their
# sweep/admission/build split (keeping `profile_summary`'s host-gap
# arithmetic honest across sequential and overlapped iterations — the
# fine split of overlapped iterations stays in the flight records).
HIST_PHASES = PHASES + ("overlap",)

# Millisecond bucket ladder for the per-phase histograms: sub-0.1 ms
# host blips through multi-second cold dispatches. Fixed at
# registration so replica snapshots merge bucket-for-bucket.
PHASE_MS_BUCKETS: tuple[float, ...] = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 5000.0)

# Histogram family (one labeled series per phase). Shared between the
# servers' eager registration and `profile_summary`'s snapshot walk.
PHASE_FAMILY = "iter_phase_ms"
_FULL_FAMILY = f"cloud_server_{PHASE_FAMILY}"

# Flight-record scalars worth carrying into the Perfetto iteration
# track's args (post-mortem context next to the phase bars).
_ITER_ARG_KEYS = ("iteration", "scheduler", "n_live", "decode_rounds",
                  "decode_tokens", "prefill_tokens", "tokens_scheduled",
                  "budget_utilization", "host_ms", "device_wait_ms",
                  "host_gap_frac", "preemptions", "pending", "n_jobs",
                  "overlap", "overlap_ms", "inflight_depth",
                  "overlap_launch_lead_ms")


class IterationProfiler:
    """Host-side phase clock for one scheduler iteration.

    `begin()` opens the iteration; `mark(phase)` attributes the time
    since the previous mark to `phase` (marks ACCUMULATE, so a phase
    visited several times in one iteration — e.g. `build`/`device`
    per chunk on the alternating scheduler — sums). Both return the
    timestamp they read so callers reuse it instead of reading the
    clock again: the mixed scheduler pays a bounded constant number
    of `perf_counter` reads per iteration (asserted by test)."""

    __slots__ = ("t0", "_last", "_acc")

    def __init__(self):
        self.t0 = 0.0
        self._last = 0.0
        self._acc: dict[str, float] = {}

    def begin(self) -> float:
        t = perf_counter()
        self.t0 = self._last = t
        self._acc = {}
        return t

    def mark(self, phase: str) -> float:
        t = perf_counter()
        acc = self._acc
        acc[phase] = acc.get(phase, 0.0) + (t - self._last)
        self._last = t
        return t

    def phases_ms(self) -> dict[str, float]:
        """Accumulated per-phase milliseconds, canonical order. The
        values partition [t0, last mark]: their sum is the elapsed
        time between those clock reads (no time is double-counted or
        dropped), which is what makes the flight record's
        `host_ms + device_wait_ms == duration_ms` hold exactly."""
        acc = self._acc
        return {p: acc[p] * 1e3 for p in PHASES if p in acc}


def register_phase_hists(registry) -> dict:
    """Eagerly register the per-phase histogram family on a server's
    registry (one labeled series per phase) and return the
    phase -> Histogram dict the per-iteration observe path indexes.
    THE one registration site for both servers: the family name, help
    text, and ms ladder must match everywhere or the router's
    bucket-for-bucket fleet merge breaks."""
    return {
        p: registry.histogram(
            PHASE_FAMILY,
            "Scheduler iteration time by phase (milliseconds)",
            buckets=PHASE_MS_BUCKETS, labels={"phase": p})
        for p in HIST_PHASES}


def resolve_profiler(profile,
                     cfg_enabled: bool = True) -> IterationProfiler | None:
    """The one constructor both servers use: `profile` may be a ready
    IterationProfiler, True/False, "off", or None (falling back to
    `InferConfig.iteration_profile`). Returns None when disabled —
    every guarded call site short-circuits and the scheduler keeps
    the exact pre-profiler clock behavior."""
    if profile is False or profile == "off":
        return None
    if isinstance(profile, IterationProfiler):
        return profile
    if profile is True:
        return IterationProfiler()
    if profile is None:
        return IterationProfiler() if cfg_enabled else None
    raise ValueError(
        "iteration_profile must be True, False, 'off', None, or an "
        f"IterationProfiler; got {profile!r}")


def derive_gap_fields(phases_ms: dict[str, float],
                      duration_ms: float,
                      overlapped: bool = False) -> dict[str, float]:
    """The derived flight-record fields from one iteration's phase
    split: host milliseconds (the SERIALIZED host work), the device
    wait, and the host-gap fraction of the iteration.

    Sequential iterations (`overlapped=False`): host = everything
    except `device` — the historical definition, byte-identical.
    Overlapped iterations: sweep/admission/build ran concurrently with
    the in-flight device program, so they move into `overlap_ms`;
    `host_ms` keeps only the residual serialized tail (commit + launch
    + epilogue) and `host_gap_frac` therefore measures what the
    overlap could NOT hide."""
    device = phases_ms.get("device", 0.0)
    if overlapped:
        overlap = sum(phases_ms.get(p, 0.0) for p in OVERLAP_PHASES)
        host = sum(v for k, v in phases_ms.items()
                   if k != "device" and k not in OVERLAP_PHASES)
        return {"host_ms": host, "device_wait_ms": device,
                "overlap_ms": overlap,
                "host_gap_frac": host / duration_ms if duration_ms > 0
                else 0.0}
    host = sum(v for k, v in phases_ms.items() if k != "device")
    return {"host_ms": host, "device_wait_ms": device,
            "host_gap_frac": host / duration_ms if duration_ms > 0
            else 0.0}


def profile_summary(snapshot: dict) -> dict | None:
    """The `/stats` `iteration_profile` payload from a metrics
    snapshot (one server's, or the router's fleet-merge — the phase
    histograms merged bucket-for-bucket upstream, so these are true
    fleet percentiles): per-phase count/mean/p50/p99 milliseconds
    plus the aggregate `host_gap_frac` recomputed from the merged
    sums (a ratio must never be added across replicas). None when no
    phase histograms are present (profiling disabled, or a backend
    without it)."""
    phases: dict[str, dict] = {}
    host_ms = device_ms = overlap_ms = 0.0
    for key, entry in snapshot.items():
        if not key.startswith(_FULL_FAMILY + "{") \
                or entry.get("type") != "histogram":
            continue
        phase = (entry.get("labels") or {}).get("phase")
        if phase is None:
            continue
        count = entry["count"]
        phases[phase] = {
            "count": count,
            "mean_ms": entry["sum"] / count if count else 0.0,
            "p50_ms": histogram_percentile(entry, 0.50),
            "p99_ms": histogram_percentile(entry, 0.99)}
        if phase == "device":
            device_ms += entry["sum"]
        elif phase == "overlap":
            # host work performed while a dispatch was in flight (the
            # async scheduler's hidden sweep/admission/build): not
            # device-idle time, so not host gap
            overlap_ms += entry["sum"]
        else:
            host_ms += entry["sum"]
    if not phases:
        return None
    total = host_ms + device_ms + overlap_ms
    return {"phases": {p: phases[p] for p in HIST_PHASES if p in phases},
            "host_ms_total": host_ms,
            "device_wait_ms_total": device_ms,
            "overlap_ms_total": overlap_ms,
            "host_gap_frac": host_ms / total if total > 0 else 0.0}


def scheduler_chrome_trace(records: list[dict]) -> dict:
    """Render flight-recorder records as Chrome trace event format
    JSON (chrome://tracing / ui.perfetto.dev): one process per
    replica, one track per phase plus an `iteration` track whose args
    carry the record's scalars. Timestamps are microseconds on the
    servers' perf_counter timebase — the SAME timebase as the
    request-trace export (`GET /traces`), and every event's args
    carry the flight-recorder `iteration` index, which is also the
    tag on every `prefill_chunk`/`decode_segment` span in a request's
    tree: the two exports cross-link in both directions ("why was
    this request's decode_segment slow" ↔ "what was the scheduler
    doing that iteration").

    Phases render laid out consecutively in canonical order inside
    the iteration window; on the alternating scheduler a phase's bar
    is its per-iteration SUM (chunks interleave build/device several
    times), so bar order within an iteration is attribution, not a
    literal interleaving. Records written with profiling disabled
    carry no `t_start`/`phases_ms` and are skipped.

    OVERLAPPED iterations (the async double-buffered scheduler) are
    NOT disjoint in device time: the program committed by iteration
    k+1 was launched inside iteration k's window. Each record that
    launched ahead carries `t_launch`, and the export renders an
    `inflight` track whose slices span from that launch to the END of
    the NEXT record's residual `device` wait — so the device slice
    visibly runs CONCURRENT with (nested under) the next iteration's
    sweep/admission/build bars instead of the export pretending
    iteration bounds partition device time."""
    events: list[dict] = []
    seen_pids: set[int] = set()
    inflight_tid = len(PHASES) + 1
    last_launch: dict[int, tuple[float, int]] = {}  # pid -> (ts, iter)
    for rec in records:
        t0 = rec.get("t_start")
        if t0 is None:
            continue
        pid = int(rec.get("replica", 0))
        if pid not in seen_pids:
            seen_pids.add(pid)
            events.append({"ph": "M", "name": "process_name",
                           "pid": pid,
                           "args": {"name": f"scheduler replica {pid}"}})
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": 0, "args": {"name": "iteration"}})
            for i, p in enumerate(PHASES):
                events.append({"ph": "M", "name": "thread_name",
                               "pid": pid, "tid": i + 1,
                               "args": {"name": p}})
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": inflight_tid,
                           "args": {"name": "inflight"}})
        args = {k: rec[k] for k in _ITER_ARG_KEYS if k in rec}
        events.append({"ph": "X",
                       "name": f"iteration {rec.get('iteration')}",
                       "ts": t0 * 1e6,
                       "dur": rec.get("duration_ms", 0.0) * 1e3,
                       "pid": pid, "tid": 0, "args": args})
        off = t0 * 1e6
        device_end = None
        for i, p in enumerate(PHASES):
            v = (rec.get("phases_ms") or {}).get(p, 0.0)
            if v <= 0:
                continue
            events.append({"ph": "X", "name": p, "ts": off,
                           "dur": v * 1e3, "pid": pid, "tid": i + 1,
                           "args": {"iteration": rec.get("iteration")}})
            off += v * 1e3
            if p == "device":
                device_end = off
        if rec.get("overlap") and pid in last_launch \
                and device_end is not None:
            # the dispatch THIS record committed: launched inside the
            # previous record's window, device-resident until this
            # record's residual sync — one concurrent slice
            ts_launch, it_launch = last_launch.pop(pid)
            events.append({"ph": "X",
                           "name": f"dispatch (committed by iteration "
                                   f"{rec.get('iteration')})",
                           "ts": ts_launch * 1e6,
                           "dur": max(device_end - ts_launch * 1e6, 0.0),
                           "pid": pid, "tid": inflight_tid,
                           "args": {"launched_in_iteration": it_launch,
                                    "iteration": rec.get("iteration")}})
        if rec.get("t_launch") is not None:
            last_launch[pid] = (rec["t_launch"], rec.get("iteration"))
    return {"traceEvents": events, "displayTimeUnit": "ms"}

"""Adaptive speculative-decoding control: per-slot draft-length tuning
from observed accept rates.

Speculation pays only when drafts are accepted: every rejected draft
token is a verify-window position the target model scored for nothing,
and `spec_drafts` as a static construction-time knob forces one length
on every request — the repetition-heavy request that accepts 3-of-3
and the random-prompt request that accepts 0-of-3 ride the same
window. This module closes the loop host-side: the scheduler already
syncs per-round committed counts (`n_acc + 1` per slot), so a rolling
accept rate per slot costs nothing extra, and the per-iteration draft
count becomes a CONTROLLED resource — each slot carries its own draft
length in [0, spec_drafts], each row commits at most its own
(`draft_limit` in `_spec_core`, the same exact truncation the
`stop_len` cap already performs), and the dispatch width stays
quantized to {0, spec_drafts} — `n_drafts` is a static shape, so
intermediate widths would cost a compile each for a sliver of verify
compute; the all-off dispatch is plain decode with no draft passes.

Control law (all knobs in `SpecControlConfig`):

  * per-slot EWMA of accepted/drafted per committed round;
  * HYSTERESIS with a cooldown: the length steps +1 when the rate
    crosses `high`, -1 when it falls under `low`, and never moves
    again for `cooldown` observed rounds — so the draft-model cache
    discipline (which is exact at ANY per-round length, see
    `_spec_core`) is not churned by single-round noise;
  * length 0 is plain decode for that slot ("off"). An n-gram slot —
    and a draft-model slot whose draft cache stayed warm (it rode at
    length 0 inside other slots' speculative windows, where the draft
    model still processes its `last` token every round) — PROBES back
    to length 1 after `probe_period` idle rounds. A draft-model slot
    that sat through plain-decode dispatches (no draft rows ran at
    all) has a STALE draft cache — positions decoded plainly were
    never draft-prefilled — so it stays off for the rest of the
    request (`on_plain_dispatch` marks it; re-admission after a
    preemption re-prefills the draft cache and clears the mark).

Exactness is never the controller's job: the accept rule commits an
exact sample at every length, including 0 (the round's single
committed token is the draft if accepted else the corrective — the
marginal is the target distribution either way), so the controller
tunes THROUGHPUT only. Greedy outputs are token-for-token identical
at any length schedule (tests/test_mixed_scheduler.py pins this
through mid-stream length changes).

Everything here is plain host arithmetic on Python ints/floats — the
controller runs inside the scheduler iteration, so it is on the
`cloud_server_tpu/analysis` hot-path lint roster: no numpy buffers,
no device work, no clocks, no I/O. Single-writer discipline: only the
scheduler thread mutates state; scrape-path readers (`accept_rate`,
`draft_lengths`) take list() copies and tolerate torn-but-plausible
values, like the flight recorder.
"""

from __future__ import annotations

import dataclasses
import json


@dataclasses.dataclass(frozen=True)
class SpecControlConfig:
    """Adaptive-speculation knobs (JSON object / string / file path via
    `InferConfig.spec_control_config`, server `spec_control=`, CLI
    `--spec-control`; the literal "off" disables adaptation — fixed
    `spec_drafts` length, the pre-adaptive behavior).

    `low`/`high` are the hysteresis thresholds on the per-slot EWMA of
    accepted-per-drafted; `ewma` is the smoothing factor (higher =
    faster reaction, noisier); `cooldown` is the minimum observed
    rounds between length changes for one slot; `probe_period` is how
    many length-0 rounds a slot waits before probing back to length 1
    (never, for a draft-model slot with a stale draft cache);
    `initial` is the admission draft length (None = spec_drafts —
    optimistic start, so high-acceptance workloads never pay a ramp)."""

    low: float = 0.30
    high: float = 0.60
    ewma: float = 0.25
    cooldown: int = 4
    probe_period: int = 64
    initial: int | None = None

    def __post_init__(self) -> None:
        if not 0.0 <= self.low < self.high <= 1.0:
            raise ValueError(
                f"need 0 <= low < high <= 1 (got low={self.low}, "
                f"high={self.high}); equal thresholds would oscillate "
                "every round")
        if not 0.0 < self.ewma <= 1.0:
            raise ValueError("ewma must be in (0, 1]")
        if self.cooldown < 1:
            raise ValueError("cooldown must be >= 1 round")
        if self.probe_period < 1:
            raise ValueError("probe_period must be >= 1 round")
        if self.initial is not None and self.initial < 0:
            raise ValueError("initial draft length must be >= 0")


class _SlotState:
    """Per-slot controller state (controller-private)."""

    __slots__ = ("length", "rate", "since_change", "zero_rounds",
                 "stale")

    def __init__(self, length: int, rate: float):
        self.length = length
        self.rate = rate
        self.since_change = 0
        self.zero_rounds = 0
        self.stale = False


class SpecController:
    """Host-side adaptive draft-length controller for one server.

    The scheduler drives it at moments it already owns:
      * `on_admit(slot)` when a slot is (re-)admitted — fresh state at
        the initial length (re-admission re-prefills the draft cache,
        so staleness clears);
      * `draft_len(slot)` when planning a dispatch (any live slot
        drafting keeps the spec program; each row's cap is its own);
      * `observe(slot, drafted, accepted)` once per committed decode
        round, from the counts the scheduler synced anyway;
      * `on_plain_dispatch(slots, rounds)` when a decode dispatch ran
        with no draft rows at all (every live length 0): draft-model
        slots go stale (their caches miss the plainly-decoded
        positions), n-gram slots accrue probe credit;
      * `on_release(slot)` at slot teardown.
    """

    def __init__(self, max_drafts: int,
                 config: SpecControlConfig | None = None, *,
                 has_draft_model: bool = False):
        if max_drafts <= 0:
            raise ValueError("adaptive speculation needs spec_drafts > 0")
        self.max_drafts = int(max_drafts)
        self.config = config if config is not None else SpecControlConfig()
        self.has_draft_model = bool(has_draft_model)
        self._initial = (self.max_drafts if self.config.initial is None
                         else min(self.config.initial, self.max_drafts))
        # neutral EWMA seed: new slots start between the thresholds so
        # neither direction fires until real rounds move the estimate
        self._neutral = 0.5 * (self.config.low + self.config.high)
        self._slots: dict[int, _SlotState] = {}
        # global rolling accept rate (the scrape-path gauge); rounds
        # with drafted == 0 carry no acceptance information and are
        # excluded, so "everything off" freezes rather than zeroes it
        self._rate = self._neutral
        self._observed_rounds = 0
        self.length_changes = 0  # lifetime, for tests/flight debugging

    # -- slot lifecycle ------------------------------------------------------

    def on_admit(self, slot_id: int) -> None:
        self._slots[slot_id] = _SlotState(self._initial, self._neutral)

    def on_release(self, slot_id: int) -> None:
        self._slots.pop(slot_id, None)

    # -- dispatch planning (hot path) ----------------------------------------

    def draft_len(self, slot_id: int) -> int:
        st = self._slots.get(slot_id)
        return self._initial if st is None else st.length

    # -- feedback (hot path) -------------------------------------------------

    def observe(self, slot_id: int, drafted: int, accepted: int) -> None:
        """One committed decode round for `slot_id`: `drafted` tokens
        were proposed on the row's behalf (its own length, not the
        dispatch width), `accepted` of them committed. drafted == 0
        rounds (the slot rode a speculative window at length 0) only
        accrue probe credit."""
        st = self._slots.get(slot_id)
        if st is None:
            return
        cfg = self.config
        if drafted <= 0:
            st.zero_rounds += 1
            if (st.length == 0 and not st.stale
                    and st.zero_rounds >= cfg.probe_period):
                st.length = 1
                st.rate = self._neutral  # a fair shot, not stale history
                st.since_change = 0
                st.zero_rounds = 0
                self.length_changes += 1
            return
        r = min(accepted, drafted) / drafted
        st.rate += cfg.ewma * (r - st.rate)
        self._rate += cfg.ewma * (r - self._rate)
        self._observed_rounds += 1
        st.since_change += 1
        if st.since_change < cfg.cooldown:
            return
        if st.rate >= cfg.high and st.length < self.max_drafts:
            st.length += 1
            st.since_change = 0
            self.length_changes += 1
        elif st.rate <= cfg.low and st.length > 0:
            st.length -= 1
            st.since_change = 0
            st.zero_rounds = 0
            self.length_changes += 1

    def on_plain_dispatch(self, slot_ids, rounds: int) -> None:
        """A decode dispatch ran with zero draft rows (every live slot
        at length 0). Draft-model slots' caches now miss the plainly
        decoded positions — sticky off; n-gram slots (cache-free) just
        accrue `rounds` of probe credit."""
        for sid in slot_ids:
            st = self._slots.get(sid)
            if st is None:
                continue
            if self.has_draft_model:
                st.stale = True
                continue
            for _ in range(rounds):
                self.observe(sid, 0, 0)

    # -- scrape-path views ---------------------------------------------------

    def accept_rate(self) -> float:
        """Rolling (EWMA) fleet accept rate over committed rounds —
        the `cloud_server_spec_accept_rate` gauge source."""
        return self._rate if self._observed_rounds else 0.0

    def draft_lengths(self) -> dict[int, int]:
        """{slot_id: current draft length} for live slots (flight
        recorder / /stats view; copied, safe off-thread)."""
        return {sid: st.length for sid, st in list(self._slots.items())}


def resolve_controller(spec, config_str: str, max_drafts: int, *,
                       has_draft_model: bool) -> SpecController | None:
    """The one constructor the paged server uses. `spec` may be a ready
    SpecController, a SpecControlConfig, a config dict / JSON string /
    file path, None (falling back to `InferConfig.spec_control_config`),
    or the literal False — adaptation force-disabled (fixed
    `spec_drafts` draft length, the bench's fixed-length arms). The
    fallback string "" selects the DEFAULT adaptive config (adaptive
    speculation is on whenever speculation is); the literal "off"
    disables it. Returns None when adaptation is off or speculation is
    not configured at all."""
    if max_drafts <= 0 or spec is False:
        return None
    if isinstance(spec, SpecController):
        if spec.max_drafts != max_drafts:
            # fail at construction: a controller planning lengths above
            # the dispatch width would overbill the drafted-token
            # ledgers and depress every accept rate by the same factor
            # (a perfectly-accepting slot could never climb)
            raise ValueError(
                f"spec_control.max_drafts={spec.max_drafts} does not "
                f"match the server's spec_drafts={max_drafts}")
        return spec
    cfg = spec if spec is not None else (config_str or "")
    if isinstance(cfg, str):
        text = cfg.strip()
        if text.lower() == "off":
            return None
        if text == "":
            cfg = SpecControlConfig()
        else:
            if not text.startswith("{"):
                with open(text) as f:  # a path, not inline JSON
                    text = f.read()
            cfg = json.loads(text)
    if isinstance(cfg, dict):
        unknown = set(cfg) - {f.name for f in
                              dataclasses.fields(SpecControlConfig)}
        if unknown:
            raise ValueError(
                f"unknown spec_control keys: {sorted(unknown)}")
        cfg = SpecControlConfig(**cfg)
    if not isinstance(cfg, SpecControlConfig):
        raise ValueError(
            "spec_control must be a SpecControlConfig, a JSON object, "
            "a file path, False, or 'off'")
    return SpecController(max_drafts, cfg,
                          has_draft_model=has_draft_model)

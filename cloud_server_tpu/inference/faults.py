"""Failure-domain layer: deterministic fault injection and overload
brownout for the serving stack.

A fleet that is supposed to survive replica failures needs two things
this repo historically lacked: a way to MAKE failures happen on demand
(so every recovery path is provable, not aspirational) and a policy for
degrading gracefully when the failure mode is plain overload rather
than a crash. Both live here, stdlib-only, and both follow the QoS/SLO
module rules: pure host-side state consulted at points the schedulers
already own, zero added dispatches or syncs (the `analysis/` hot-path
lint, DD3 jax-free host-policy pass, and lock-discipline pass all
roster this file; the `_mixed_step` dispatch/device_get-count
regression clones pin the runtime side).

Deterministic fault injection
-----------------------------

`FaultPlan` arms named SITES the servers thread through their hot
paths (each call site guarded by ``if self._faults is not None`` so an
unconfigured server runs the byte-identical pre-fault code):

  * ``submit_reject``    — submit() raises `InjectedFault` (both
                           servers): exercises router failover on
                           submit and client 503 handling.
  * ``dispatch``         — the next dispatch path raises
                           `InjectedFault` before launching device
                           work (paged: `_mixed_dispatch` /
                           `_decode_dispatch` / `_run_one_chunk`;
                           contiguous: `_step_locked`): the scheduler
                           thread crashes exactly the way a poisoned
                           device program would, driving
                           `serve_forever` -> `_fail_all` -> router
                           retry.
  * ``iteration_stall``  — step() sleeps `stall_ms` before the sweep
                           (both servers): simulates a slow host or a
                           long device round, the input the brownout
                           detector and SLO burn rates key on.
  * ``wedge``            — step() blocks (holding `_step_lock`) until
                           the server's stop event is set (paged
                           only): the "scheduler wedged inside a
                           dispatch" shape `_fail_all`'s bounded
                           lock acquire exists for.
  * ``alloc_famine``     — the next admission pretends the page pool
                           is empty (paged only): exercises the
                           famine-retry / preemption paths without
                           shrinking the pool.
  * ``migrate_export``   — the next migration export raises
                           `InjectedFault` before snapshotting (paged
                           only): exercises the non-migratable
                           fallback (the request fails fast with
                           today's `retriable: false` body).
  * ``migrate_import``   — the next migration import raises
                           `InjectedFault` on the destination (paged
                           only): exercises the router's
                           import-failure path (failure stands on the
                           original handle).

Plans are SEEDED: a spec may fire probabilistically (``p < 1``) and
the draw sequence comes from one `random.Random(seed)`, so a given
plan against a given request sequence reproduces exactly. Config is a
JSON object (inline string, dict, or file path) via the server
``faults=`` kwarg / `InferConfig.fault_plan` / CLI ``--fault-plan``::

    {"seed": 0,
     "faults": [
       {"site": "dispatch", "after": 10, "count": 1},
       {"site": "submit_reject", "after": 0, "count": 0, "p": 0.01},
       {"site": "iteration_stall", "stall_ms": 250, "count": 5}]}

``after`` skips the first N hits of the site, ``count`` bounds how
many times the spec fires (<= 0 = unlimited), ``p`` is the per-hit
probability once eligible. Tests can also `plan.arm(site, ...)` at
runtime for exact-moment injection.

Overload brownout
-----------------

`OverloadDetector` watches the per-iteration signals the flight
recorder already owns — pending-queue head age, token-budget
utilization, `host_gap_frac` — as EWMAs, and grades overload into
levels: 0 (healthy), 1 (one signal over threshold), 2 (two or more).
The paged server feeds it from `_record_iteration` (one `observe()`
per busy iteration, plain float math) and consults it at submit:
while the level is high, admissions whose QoS priority class is in
the level's shed set (best_effort at level 1; batch too at level 2)
are refused with `BrownoutShedError` — an HTTP 429 carrying the PR 5
`Retry-After` shape — so interactive traffic keeps its SLO while the
fleet browns out instead of collapsing. The computed retry hint
carries deterministic JITTER (seeded, ``retry_after_s`` base plus up
to ``jitter_frac`` of it) so a synchronized cohort of shed clients
does not thundering-herd the recovering replica. Config (server
``brownout=`` / `InferConfig.brownout_config` / ``--brownout``)::

    {"pending_age_s": 2.0, "budget_utilization": 0.95,
     "host_gap_frac": 0.5, "alpha": 0.3, "hold_s": 2.0,
     "retry_after_s": 1.0, "jitter_frac": 0.5, "seed": 0,
     "shed": {"1": ["best_effort"], "2": ["best_effort", "batch"]}}

Brownout requires a QoS registry (shed sets are priority classes);
without one every request is anonymous and nothing is shed.
"""

from __future__ import annotations

import dataclasses
import json
import random
import threading
import time

# imported like qos.py does (the servers import this module lazily, so
# there is no cycle); keeps BrownoutShedError on the HTTP 429 path
from cloud_server_tpu.inference.server import QueueFullError

# The named injection sites the servers thread. Order is documentation
# only; membership is validated at spec construction so a typo'd site
# fails the plan parse, not silently never-fires.
SITES = ("submit_reject", "dispatch", "iteration_stall", "wedge",
         "alloc_famine", "migrate_export", "migrate_import")


class InjectedFault(RuntimeError):
    """A deliberately injected failure (never raised without an armed
    FaultPlan). Subclasses RuntimeError so every layer above treats it
    exactly like a real scheduler/server error — which is the point."""


class BrownoutShedError(QueueFullError):
    """Overload brownout refused this admission: the replica is
    shedding the request's priority class to protect higher classes'
    SLOs. Retryable — the HTTP front-end maps it to a 429 whose
    `Retry-After` header and structured body carry the detector's
    jittered `retry_after_s` (PR 5 shape)."""

    def __init__(self, message: str, *, tenant: str | None,
                 priority_class: str, retry_after_s: float):
        super().__init__(message)
        self.tenant = tenant
        self.priority_class = priority_class
        self.retry_after_s = float(retry_after_s)


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One armed fault: fire at `site`, skipping the first `after`
    hits, at most `count` times (<= 0 = unlimited), each eligible hit
    firing with probability `p`. `stall_ms` is the sleep for
    `iteration_stall` (ignored elsewhere)."""

    site: str
    after: int = 0
    count: int = 1
    p: float = 1.0
    stall_ms: float = 0.0

    def __post_init__(self) -> None:
        if self.site not in SITES:
            raise ValueError(
                f"unknown fault site {self.site!r}; one of {SITES}")
        if self.after < 0:
            raise ValueError("fault 'after' must be >= 0")
        if not 0.0 <= self.p <= 1.0:
            raise ValueError("fault 'p' must be in [0, 1]")
        if self.stall_ms < 0:
            raise ValueError("fault 'stall_ms' must be >= 0")


class FaultPlan:
    """A seeded set of armed fault sites. `fire()` (and the `check` /
    `maybe_stall` / `maybe_wedge` conveniences over it) is the only
    hot-path surface: one lock-guarded counter bump plus a few int
    compares per guarded site hit — and call sites only exist behind
    ``if self._faults is not None``, so the unconfigured servers pay
    literally nothing."""

    def __init__(self, spec: dict | None = None):
        spec = dict(spec or {})
        seed = int(spec.pop("seed", 0))
        raw = list(spec.pop("faults", ()))
        if spec:
            raise ValueError(
                f"unknown fault-plan keys: {sorted(spec)}")
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._specs: dict[str, list[list]] = {s: [] for s in SITES}
        # per-site lifetime hit / fired counts (the /stats + test
        # observability surface)
        self.hits: dict[str, int] = {s: 0 for s in SITES}
        self.fired: dict[str, int] = {s: 0 for s in SITES}
        for entry in raw:
            if not isinstance(entry, dict):
                raise ValueError("each fault must be a JSON object")
            self.arm(**entry)

    def arm(self, site: str, *, after: int = 0, count: int = 1,
            p: float = 1.0, stall_ms: float = 0.0) -> FaultSpec:
        """Arm one spec (config entries and tests share this); the
        spec's `after` window counts from the site's CURRENT hit
        count, so a test can arm "the very next dispatch" on a live
        server deterministically."""
        fs = FaultSpec(site=site, after=after, count=count, p=p,
                       stall_ms=stall_ms)
        with self._lock:
            # [spec, first-eligible hit index, times fired]
            self._specs[site].append([fs, self.hits[site] + after, 0])
        return fs

    def fire(self, site: str) -> FaultSpec | None:
        """Count one hit of `site`; return the armed spec that fires
        on this hit (first eligible wins), else None. Deterministic
        given the plan seed and the sequence of fire() calls."""
        with self._lock:
            idx = self.hits[site]
            self.hits[site] = idx + 1
            for rec in self._specs[site]:
                fs, start, used = rec
                if idx < start:
                    continue
                if fs.count > 0 and used >= fs.count:
                    continue
                if fs.p < 1.0 and self._rng.random() >= fs.p:
                    continue
                rec[2] = used + 1
                self.fired[site] += 1
                return fs
        return None

    def check(self, site: str) -> None:
        """fire() and raise `InjectedFault` when armed — the raising
        sites (submit_reject, dispatch)."""
        if self.fire(site) is not None:
            raise InjectedFault(
                f"injected fault at site {site!r}")

    # -- blocking sites (deliberately NOT on the hot-path lint roster:
    # sleeping/waiting is exactly their injected behavior) -------------------

    def maybe_stall(self, site: str = "iteration_stall") -> None:
        """Sleep `stall_ms` when the stall site fires (the scheduler
        thread pays it, exactly like a slow host/device round)."""
        fs = self.fire(site)
        if fs is not None and fs.stall_ms > 0:
            time.sleep(fs.stall_ms / 1e3)

    def maybe_wedge(self, stop_event: threading.Event,
                    site: str = "wedge") -> None:
        """Block the calling (scheduler) thread until the server's
        stop event is set, simulating a wedge inside a dispatch. The
        thread still holds `_step_lock` while wedged — which is the
        scenario `_fail_all`'s bounded acquire and the
        `unserialized_teardown` counter exist for."""
        if self.fire(site) is not None:
            stop_event.wait()

    def stats(self) -> dict:
        """Per-site lifetime hit/fired counts (scrape path)."""
        with self._lock:
            return {"hits": dict(self.hits), "fired": dict(self.fired)}


def _resolve_config(value, fallback: str, cls, what: str):
    """The shared resolution chain `faults=` and `brownout=` both
    follow (one copy, so the two contracts cannot drift): a ready
    `cls` instance passes through; False force-disables regardless of
    the config fallback; None falls back to the InferConfig string; a
    dict / inline-JSON string / file path parses; ""/None resolves to
    None (feature fully disabled)."""
    if value is False:
        return None
    if isinstance(value, cls):
        return value
    spec = value if value is not None else (fallback or None)
    if spec is None or spec == "":
        return None
    if isinstance(spec, str):
        text = spec
        if not text.lstrip().startswith("{"):
            with open(text) as f:  # a path, not inline JSON
                text = f.read()
        spec = json.loads(text)
    if not isinstance(spec, dict):
        raise ValueError(f"{what} must be a JSON object")
    return cls(spec)


def resolve_fault_plan(faults, fault_plan_config: str = ""
                       ) -> FaultPlan | None:
    """The one constructor both servers use: `faults` may be a ready
    FaultPlan, a config dict, a JSON string, a file path, None
    (falling back to `InferConfig.fault_plan`), or False — injection
    force-disabled regardless of the config fallback. Returns None
    (no plan: every guarded call site short-circuits, byte-identical
    pre-fault scheduling) when nothing is configured."""
    return _resolve_config(faults, fault_plan_config, FaultPlan,
                           "fault plan")


# ---------------------------------------------------------------------------
# Overload brownout
# ---------------------------------------------------------------------------


# Signals and their default thresholds — all numbers the flight
# recorder already carries per busy iteration, so the detector adds
# zero measurement cost of its own.
_SIGNAL_DEFAULTS = {
    "pending_age_s": 2.0,        # age of the pending-queue head
    "budget_utilization": 0.95,  # mixed token-budget saturation
    "host_gap_frac": 0.5,        # host share of the iteration
}

DEFAULT_SHED: dict[int, tuple[str, ...]] = {
    1: ("best_effort",),
    2: ("best_effort", "batch"),
}


class OverloadDetector:
    """EWMA overload grading over per-iteration scheduler signals.

    `observe()` runs once per busy iteration on the scheduler thread
    (plain float math under a small lock); `level()` / `shed()` run on
    submit threads. Levels: 0 healthy, 1 = one signal EWMA over its
    threshold (shed best_effort), 2 = two or more (shed batch too).
    A risen level HOLDS for `hold_s` after the signals recover
    (hysteresis — admission must not flap open/shut every iteration).

    `retry_hint()` is the Retry-After the shed 429s carry:
    ``retry_after_s * level`` plus a seeded uniform jitter of up to
    ``jitter_frac`` of that base, so shed clients that all woke at the
    same moment re-arrive spread out instead of as a second stampede
    at the recovering replica."""

    def __init__(self, config: dict | None = None, *,
                 clock=time.monotonic):
        cfg = dict(config or {})
        self._clock = clock
        self._thresholds = {}
        for name, default in _SIGNAL_DEFAULTS.items():
            self._thresholds[name] = float(cfg.pop(name, default))
        self.alpha = float(cfg.pop("alpha", 0.3))
        if not 0.0 < self.alpha <= 1.0:
            raise ValueError("brownout alpha must be in (0, 1]")
        self.hold_s = float(cfg.pop("hold_s", 2.0))
        self.retry_after_s = float(cfg.pop("retry_after_s", 1.0))
        self.jitter_frac = float(cfg.pop("jitter_frac", 0.5))
        if self.jitter_frac < 0:
            raise ValueError("brownout jitter_frac must be >= 0")
        self._rng = random.Random(int(cfg.pop("seed", 0)))
        shed = cfg.pop("shed", None)
        if shed is None:
            self._shed = dict(DEFAULT_SHED)
        else:
            self._shed = {int(lvl): tuple(classes)
                          for lvl, classes in dict(shed).items()}
        if cfg:
            raise ValueError(
                f"unknown brownout config keys: {sorted(cfg)}")
        self._lock = threading.Lock()
        self._ewma = {name: 0.0 for name in _SIGNAL_DEFAULTS}
        self._level = 0
        self._level_ts = clock()
        self._observe_ts = self._level_ts
        # per-class lifetime shed counts (scrape-path mirror source)
        self.shed_total: dict[str, int] = {}

    def observe(self, *, pending_age_s: float = 0.0,
                budget_utilization: float = 0.0,
                host_gap_frac: float = 0.0) -> int:
        """Fold one busy iteration's signals in; returns the current
        level. Called by the scheduler once per busy iteration; one
        monotonic clock read (the detector keeps its OWN timebase so
        hysteresis and staleness compare like with like)."""
        now = self._clock()
        a = self.alpha
        with self._lock:
            ew = self._ewma
            ew["pending_age_s"] += a * (pending_age_s
                                        - ew["pending_age_s"])
            ew["budget_utilization"] += a * (budget_utilization
                                             - ew["budget_utilization"])
            ew["host_gap_frac"] += a * (host_gap_frac
                                        - ew["host_gap_frac"])
            crossed = sum(1 for name, th in self._thresholds.items()
                          if ew[name] > th)
            raw = 2 if crossed >= 2 else (1 if crossed else 0)
            self._observe_ts = now
            if raw >= self._level:
                self._level = raw
                self._level_ts = now
            elif now - self._level_ts >= self.hold_s:
                # hysteresis: only step DOWN after hold_s of recovery
                self._level = raw
                self._level_ts = now
            return self._level

    def _effective_locked(self, now: float) -> int:
        """Current level, decayed to 0 when no busy iteration has
        observed for hold_s — an idle scheduler is by definition not
        overloaded, and a latched shed level must never refuse the
        very traffic whose admission would prove recovery."""
        if self._level and now - self._observe_ts > self.hold_s:
            self._level = 0
            self._level_ts = now
        return self._level

    def level(self) -> int:
        with self._lock:
            return self._effective_locked(self._clock())

    def shed(self, priority_class: str | None) -> bool:
        """Should an admission of `priority_class` be refused right
        now? True increments the class's shed counter (the caller
        raises BrownoutShedError next)."""
        with self._lock:
            lvl = self._effective_locked(self._clock())
            classes = self._shed.get(lvl, ())
            if priority_class is None or priority_class not in classes:
                return False
            self.shed_total[priority_class] = (
                self.shed_total.get(priority_class, 0) + 1)
            return True

    def retry_hint(self) -> float:
        """Jittered Retry-After seconds for a shed admission."""
        with self._lock:
            base = self.retry_after_s * max(self._level, 1)
            return base + self._rng.random() * self.jitter_frac * base

    def stats(self) -> dict:
        """The /stats `brownout` block (scrape path)."""
        with self._lock:
            return {"level": self._effective_locked(self._clock()),
                    "signals": dict(self._ewma),
                    "thresholds": dict(self._thresholds),
                    "shed_total": dict(self.shed_total)}


def resolve_brownout(brownout, brownout_config: str = ""
                     ) -> OverloadDetector | None:
    """Same resolution contract as `resolve_fault_plan` (shared
    `_resolve_config` chain): a ready OverloadDetector, a config dict
    / JSON string / file path, None (falling back to
    `InferConfig.brownout_config`), or False. None means brownout
    fully disabled (no detector, no shed checks)."""
    return _resolve_config(brownout, brownout_config, OverloadDetector,
                           "brownout config")

"""Per-priority-class serving SLOs: targets, rolling multi-window
attainment, and burn rates.

PR 3's latency histograms answer "what are my percentiles"; an
autoscaler (ROADMAP item 5) and a disaggregated fleet planner (item 3)
need a different shape of signal: "is each QoS class meeting its
latency objective RIGHT NOW, and how fast is it eating its error
budget". That is the Google SRE Workbook's multi-window burn-rate
construction, applied to the serving stack's four request-latency
metrics:

    ttft         submit → first emitted token
    itl          gap between consecutive emitted tokens
    queue_wait   submit → first admission into a slot
    e2e          submit → terminal state

Each configured CLASS (named after the QoS priority classes —
`interactive` / `batch` / `best_effort` — plus `default` for traffic
with no QoS registry) declares per-metric latency targets and one
attainment objective. Every observation is a good/bad event (latency
<= target?) counted into a bucketed ring per (class, metric); reads
sum the ring over each configured window. Definitions:

    attainment  = good / total over the window (None until data)
    burn_rate   = (1 - attainment) / (1 - objective)

Burn rate 1.0 means the class is consuming error budget exactly at
the rate that exhausts it at the objective horizon; a multi-window
alert (e.g. burn > 14 over 5m AND over 1h) is the standard paging
rule, and the fleet autoscaler's input is the same number.

Design rules (shared with `serving_metrics` / `request_trace`):

  * **Zero new device work.** `observe()` is integer arithmetic on a
    preallocated ring, fed timestamps the scheduler already recorded
    (the `analysis/` hot-path lint covers it; the dispatch-count
    regression test runs with SLO tracking enabled).
  * **No configuration, no cost.** With no `slo_config` the tracker
    is None and every call site is guarded — the serving path is
    byte-identical to the pre-SLO build.
  * **Mergeable reports.** `report()` carries raw good/total counts
    per window, so `merge_reports` (used by
    `ReplicatedRouter.slo_report`) sums them exactly and recomputes
    attainment/burn fleet-wide — never an average of ratios.

Config JSON shape (`InferConfig.slo_config`, server `slo=`, CLI
`--slo-config`; a JSON object, a JSON string, or a file path)::

    {"windows_s": [60, 300, 3600],
     "classes": {
       "interactive": {"objective": 0.99, "ttft_s": 0.5, "itl_s": 0.1,
                       "queue_wait_s": 0.25, "e2e_s": 30.0},
       "batch":       {"objective": 0.95, "ttft_s": 5.0, "e2e_s": 120.0},
       "default":     {"objective": 0.99, "e2e_s": 60.0}}}

A request's class is its tenant's QoS priority class when a
TenantRegistry is configured, else `default`; classes observed but
not configured fall back to the `default` entry (absent that, the
observation is dropped — unconfigured traffic costs nothing).
Metrics without a target in a class are not tracked for it.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time

DEFAULT_CLASS = "default"
SLO_METRICS = ("ttft", "itl", "queue_wait", "e2e")
DEFAULT_WINDOWS_S = (60.0, 300.0, 3600.0)


@dataclasses.dataclass(frozen=True)
class ClassSLO:
    """One class's latency targets (seconds) + attainment objective.
    None disables that metric for the class."""

    name: str
    objective: float = 0.99
    ttft_s: float | None = None
    itl_s: float | None = None
    queue_wait_s: float | None = None
    e2e_s: float | None = None

    def __post_init__(self) -> None:
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"slo class {self.name!r}: objective must be in (0, 1) "
                "(1.0 leaves no error budget; burn rate would divide "
                "by zero)")
        for m in SLO_METRICS:
            t = getattr(self, m + "_s")
            if t is not None and t <= 0:
                raise ValueError(
                    f"slo class {self.name!r}: {m}_s must be > 0")
        if all(getattr(self, m + "_s") is None for m in SLO_METRICS):
            raise ValueError(
                f"slo class {self.name!r} declares no targets; drop the "
                "entry instead")

    def target(self, metric: str) -> float | None:
        return getattr(self, metric + "_s")


class _RollingCounts:
    """Good/total event counts over bucketed monotonic time: a
    fixed-size ring sized to the longest window, one slot per
    `bucket_s`. `observe` touches exactly one slot (stale slots are
    lazily reused via their absolute-bucket stamp) under a plain lock
    — the scheduler thread and a client-thread cancellation can
    observe the same ring concurrently, the contention shape the
    metrics Histogram locks for; `window` sums the slots whose stamp
    falls inside the asked window — a read-path scan, never a
    serving-path one."""

    def __init__(self, max_window_s: float, bucket_s: float):
        self.bucket_s = float(bucket_s)
        self.n = int(max_window_s / bucket_s) + 1
        self._stamp = [-1] * self.n   # absolute bucket index per slot
        self._good = [0] * self.n
        self._total = [0] * self.n
        self.good_lifetime = 0
        self.total_lifetime = 0
        self._lock = threading.Lock()

    def observe(self, ok: bool, now: float) -> None:
        b = int(now / self.bucket_s)
        i = b % self.n
        with self._lock:
            if self._stamp[i] != b:
                self._stamp[i] = b
                self._good[i] = 0
                self._total[i] = 0
            self._total[i] += 1
            self.total_lifetime += 1
            if ok:
                self._good[i] += 1
                self.good_lifetime += 1

    def window(self, window_s: float, now: float) -> tuple[int, int]:
        """(good, total) over the trailing `window_s` ending at `now`
        (the current partial bucket included)."""
        b = int(now / self.bucket_s)
        lo = b - int(window_s / self.bucket_s)
        good = total = 0
        with self._lock:
            for i in range(self.n):
                if lo < self._stamp[i] <= b:
                    good += self._good[i]
                    total += self._total[i]
        return good, total


def _burn(good: int, total: int, objective: float) -> float:
    if total <= 0:
        return 0.0
    return (1.0 - good / total) / (1.0 - objective)


def _attainment(good: int, total: int) -> float | None:
    return None if total <= 0 else good / total


class SLOTracker:
    """All SLO state for one server: per-(class, metric) rolling
    counts plus the parsed targets. `observe` is the only serving-path
    entry point; `report`/`mirror_metrics` run on the scrape path.

    Thread-safety: each ring guards its counts with a small lock (the
    metrics Histogram discipline — a scheduler-thread emit and a
    client-thread cancellation may observe concurrently), held for a
    handful of int ops only."""

    def __init__(self, config: dict | None = None, *,
                 clock=time.perf_counter):
        config = dict(config or {})
        unknown = set(config) - {"windows_s", "bucket_s", "classes"}
        if unknown:
            raise ValueError(f"unknown slo config keys: {sorted(unknown)}")
        windows = tuple(float(w)
                        for w in config.get("windows_s", DEFAULT_WINDOWS_S))
        if (not windows or sorted(windows) != list(windows)
                or len(set(windows)) != len(windows)
                or windows[0] <= 0):
            raise ValueError(
                "slo windows_s must be a strictly increasing sequence of "
                "positive seconds")
        self.windows = windows
        # bucket granularity: ~60 buckets across the shortest window,
        # floored at 0.25 s (finer would bloat the longest window's
        # ring for no read-out precision anyone alerts on)
        self.bucket_s = float(config.get("bucket_s",
                                         max(windows[0] / 60.0, 0.25)))
        if self.bucket_s <= 0 or self.bucket_s > windows[0]:
            raise ValueError(
                "slo bucket_s must be positive and no larger than the "
                "shortest window")
        classes = dict(config.get("classes", {}))
        if not classes:
            raise ValueError(
                'slo config declares no "classes"; nothing to track')
        self.classes: dict[str, ClassSLO] = {}
        for name, spec in classes.items():
            self.classes[name] = ClassSLO(name=name, **dict(spec))
        self._clock = clock
        self._counts: dict[tuple[str, str], _RollingCounts] = {}
        for name, cls in self.classes.items():
            for m in SLO_METRICS:
                if cls.target(m) is not None:
                    self._counts[(name, m)] = _RollingCounts(
                        windows[-1], self.bucket_s)

    # -- serving path -------------------------------------------------------

    def resolve_class(self, name: str | None) -> str | None:
        """Configured class for an observed class name: exact match,
        else the `default` entry, else None (drop)."""
        if name is not None and name in self.classes:
            return name
        if DEFAULT_CLASS in self.classes:
            return DEFAULT_CLASS
        return None

    def observe(self, cls: str | None, metric: str, value: float,
                now: float) -> None:
        """Count one latency observation (seconds) for `cls` at host
        moment `now` (the same perf_counter timestamp the metrics
        layer observed — no clock is read here)."""
        name = self.resolve_class(cls)
        if name is None:
            return
        rc = self._counts.get((name, metric))
        if rc is None:
            return  # metric untracked for this class
        rc.observe(value <= self.classes[name].target(metric), now)

    def exceeds_target(self, cls: str | None, metric: str,
                       value: float) -> bool:
        """Did `value` miss the class's target for `metric`? False for
        untracked classes/metrics — the tail-retention predicate's SLO
        clause (per-replica deterministic: targets are static config,
        identical fleet-wide by construction)."""
        name = self.resolve_class(cls)
        if name is None:
            return False
        target = self.classes[name].target(metric)
        return target is not None and value > target

    # -- read path ----------------------------------------------------------

    def burn_rates(self, now: float | None = None
                   ) -> dict[str, dict[str, tuple[float, float]]]:
        """{class: {metric: (shortest-window burn, longest-window
        burn)}} — the two numbers a multi-window burn alert compares
        (`anomaly.py`'s `slo_burn` rule samples this instead of the
        full `report()`, which builds the whole mergeable dict)."""
        now = self._clock() if now is None else now
        fast_w, slow_w = self.windows[0], self.windows[-1]
        out: dict[str, dict[str, tuple[float, float]]] = {}
        for (name, metric), rc in self._counts.items():
            obj = self.classes[name].objective
            fast = _burn(*rc.window(fast_w, now), obj)
            slow = (fast if slow_w == fast_w
                    else _burn(*rc.window(slow_w, now), obj))
            out.setdefault(name, {})[metric] = (fast, slow)
        return out

    def report(self, now: float | None = None) -> dict:
        """Attainment + burn rate per class, metric, and window, with
        the raw good/total counts that make reports mergeable
        (`merge_reports`). Window keys are the window length in
        seconds as `%g` strings ("60", "0.5" — JSON-stable and
        non-lossy, so two distinct configured windows can never
        collide into one entry)."""
        now = self._clock() if now is None else now
        classes = {}
        for name, cls in self.classes.items():
            metrics = {}
            for m in SLO_METRICS:
                rc = self._counts.get((name, m))
                if rc is None:
                    continue
                wins = {}
                for w in self.windows:
                    good, total = rc.window(w, now)
                    wins[f"{w:g}"] = {
                        "good": good, "total": total,
                        "attainment": _attainment(good, total),
                        "burn_rate": _burn(good, total, cls.objective)}
                metrics[m] = {
                    "target_s": cls.target(m), "windows": wins,
                    "lifetime": {
                        "good": rc.good_lifetime,
                        "total": rc.total_lifetime,
                        "attainment": _attainment(rc.good_lifetime,
                                                  rc.total_lifetime),
                        "burn_rate": _burn(rc.good_lifetime,
                                           rc.total_lifetime,
                                           cls.objective)}}
            classes[name] = {"objective": cls.objective,
                             "metrics": metrics}
        return {"windows_s": list(self.windows), "classes": classes}

    def mirror_metrics(self, registry, now: float | None = None) -> None:
        """Scrape-path mirror into a `serving_metrics` registry:
        `slo_attainment` / `slo_burn_rate` gauges labeled by class,
        metric, and window. Attainment with no data mirrors as 1.0
        (an idle class is not missing its SLO). Behind a router these
        ratio gauges are recomputed from the fleet-merged report, the
        `tenant_fair_share` rule."""
        rep = self.report(now)
        for cname, centry in rep["classes"].items():
            for metric, m in centry["metrics"].items():
                for w, wentry in m["windows"].items():
                    lbl = {"class": cname, "metric": metric,
                           "window_s": w}
                    att = wentry["attainment"]
                    registry.gauge(
                        "slo_attainment",
                        "Fraction of observations meeting the class "
                        "SLO target over the window",
                        labels=lbl).set(1.0 if att is None else att)
                    registry.gauge(
                        "slo_burn_rate",
                        "Error-budget burn rate over the window "
                        "(1.0 = budget exhausts at the objective "
                        "horizon)",
                        labels=lbl).set(wentry["burn_rate"])


def merge_reports(reports) -> dict | None:
    """Fleet-wide SLO report: per-replica reports' good/total counts
    sum per (class, metric, window); attainment and burn recompute
    from the sums (ratios never average). Objectives/targets come
    from the first report carrying the class — identical everywhere
    by construction (one config serves the fleet)."""
    reports = [r for r in reports if r and r.get("classes")]
    if not reports:
        return None
    out = {"windows_s": list(reports[0]["windows_s"]), "classes": {}}
    for rep in reports:
        if list(rep["windows_s"]) != out["windows_s"]:
            raise ValueError(
                "slo reports have mismatched windows across replicas; "
                "merge needs one shared slo config")
        for cname, centry in rep["classes"].items():
            cur = out["classes"].setdefault(
                cname, {"objective": centry["objective"], "metrics": {}})
            for metric, m in centry["metrics"].items():
                tgt = cur["metrics"].setdefault(
                    metric, {"target_s": m["target_s"], "windows": {},
                             "lifetime": {"good": 0, "total": 0}})
                for w, wentry in m["windows"].items():
                    dst = tgt["windows"].setdefault(
                        w, {"good": 0, "total": 0})
                    dst["good"] += wentry["good"]
                    dst["total"] += wentry["total"]
                tgt["lifetime"]["good"] += m["lifetime"]["good"]
                tgt["lifetime"]["total"] += m["lifetime"]["total"]
    for cname, centry in out["classes"].items():
        obj = centry["objective"]
        for m in centry["metrics"].values():
            for dst in list(m["windows"].values()) + [m["lifetime"]]:
                dst["attainment"] = _attainment(dst["good"], dst["total"])
                dst["burn_rate"] = _burn(dst["good"], dst["total"], obj)
    return out


def resolve_slo(slo, slo_config: str = "") -> SLOTracker | None:
    """The one constructor both servers use: `slo` may be a ready
    SLOTracker, a config dict, a JSON string, a file path, None
    (falling back to `InferConfig.slo_config`), or False — SLO
    tracking force-disabled regardless of the config fallback.
    Returns None (tracking fully disabled, byte-identical pre-SLO
    serving) when nothing is configured."""
    if slo is False:
        return None
    if isinstance(slo, SLOTracker):
        return slo
    spec = slo if slo is not None else (slo_config or None)
    if spec is None or spec == "":
        return None
    if isinstance(spec, str):
        text = spec
        if not text.lstrip().startswith("{"):
            with open(text) as f:  # a path, not inline JSON
                text = f.read()
        spec = json.loads(text)
    if not isinstance(spec, dict):
        raise ValueError("slo config must be a JSON object")
    return SLOTracker(spec)

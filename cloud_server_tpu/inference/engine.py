"""Autoregressive inference engine: KV cache, prefill + decode, generate.

TPU-first shape discipline: the cache is a static (L, B, max_len, KH, Dh)
buffer; prefill fills the prompt region in one batched pass (full MXU
utilisation), then decode steps run S=1 attention against the cache under a
single `lax.scan` inside one jit — no per-token dispatch, no dynamic
shapes, no host round-trips. Sampling (greedy/temp/top-k/top-p) happens
on-device between steps; finished sequences keep "generating" pad tokens so
shapes stay static (standard SPMD practice).

Ragged batches are first-class: right-pad prompts to a common length and
pass `lengths` (B,) — prefill tracks per-sequence cache lengths, decode
writes each sequence's k/v at its own position and masks attention to the
valid cache region, and RoPE positions are per-sequence. (Causality means
real tokens never attend to the trailing pads, so right-padding is exact.)

The transformer math itself (qkv projection + rope, output projection, MLP,
unembed) is imported from `models.transformer` — the engine owns only the
cache plumbing, so inference can never drift numerically from training.

Sharding: cache heads ride the same `tp` axis as attention weights; batch
rides (dp, fsdp). `generate` is jit-compatible and can be wrapped with
shardings by the serving layer.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from cloud_server_tpu.config import InferConfig, ModelConfig
from cloud_server_tpu.inference.sampling import sample_logits
from cloud_server_tpu.models import transformer
from cloud_server_tpu.ops import causal_attention, rms_norm, rope_table


class KVCache(NamedTuple):
    k: jnp.ndarray  # (L, B, max_len, KH, Dh) — cfg.dtype, or int8 when
    #                 cfg.kv_cache_dtype == "int8"
    v: jnp.ndarray  # (L, B, max_len, KH, Dh)
    length: jnp.ndarray  # (B,) int32 — valid entries per sequence
    # int8 mode only: per-(position, head) absmax scales, else None
    k_scale: jnp.ndarray | None = None  # (L, B, max_len, KH, 1) f32
    v_scale: jnp.ndarray | None = None


def _kv_quant(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric int8 quantization over the last (head_dim) axis.

    Per-(position, head) absmax scaling keeps error ~0.5% while halving
    cache MEMORY vs bf16 — the cap on concurrent slots x context.
    Decode-time cost: the scales fold into attention scores/probs
    (`causal_attention(k_scale=...)` and the paged kernel), so no
    dequantized cache copy is ever materialised; see docs/serving.md for
    the measured throughput numbers."""
    scale = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                    keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.round(x.astype(jnp.float32) / scale)
    return q.astype(jnp.int8), scale


def _kv_dequant(q: jnp.ndarray, scale: jnp.ndarray, dtype) -> jnp.ndarray:
    """Inverse of `_kv_quant` — test/reference use only. The hot paths
    never call it: materialising the dequantized cache costs a full-cache
    HBM round-trip per layer (a measured ~36% of decode throughput), so
    attention instead folds the scales into scores/probs and consumes the
    int8 buffers directly (`causal_attention(k_scale=..., v_scale=...)`)."""
    return (q.astype(jnp.float32) * scale).astype(dtype)


def _mlp_apply(x, lp, cfg: ModelConfig, lora=None):
    """Dense or MoE MLP residual block, chosen by cfg.num_experts.

    MoE routing at inference is per-call: prefill routes over the prompt
    batch, each decode step over its B single tokens. Capacity therefore
    differs from training's full-batch routing — exact parity with the
    training forward holds only when nothing drops (generous
    expert_capacity_factor), which is also the sane serving configuration.

    `lora`: per-row multi-adapter deltas (dense MLP only; the server
    rejects MLP-targeting adapters on MoE bases).
    """
    if cfg.num_experts >= 2:
        from cloud_server_tpu.models import moe
        x, _ = moe.moe_mlp_block(x, lp, cfg)
        return x
    return transformer.mlp_block(x, lp, cfg, lora=lora)


def init_cache(cfg: ModelConfig, batch: int, max_len: int) -> KVCache:
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
    if cfg.kv_cache_dtype == "int8":
        sshape = shape[:-1] + (1,)
        return KVCache(k=jnp.zeros(shape, jnp.int8),
                       v=jnp.zeros(shape, jnp.int8),
                       length=jnp.zeros((batch,), jnp.int32),
                       k_scale=jnp.zeros(sshape, jnp.float32),
                       v_scale=jnp.zeros(sshape, jnp.float32))
    if cfg.kv_cache_dtype != "model":
        raise ValueError(
            f"unknown kv_cache_dtype: {cfg.kv_cache_dtype!r} "
            "(expected 'model' or 'int8')")
    dtype = jnp.dtype(cfg.dtype)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   length=jnp.zeros((batch,), jnp.int32))


# ---------------------------------------------------------------------------
# Prefill
# ---------------------------------------------------------------------------

def prefill(params, tokens: jnp.ndarray, cfg: ModelConfig, cache: KVCache,
            lengths: jnp.ndarray | None = None
            ) -> tuple[jnp.ndarray, KVCache]:
    """Run the prompt (B, P) through the model, populating cache[:, :, :P].

    Args:
      tokens: (B, P) int32, right-padded when ragged.
      lengths: optional (B,) int32 valid prompt lengths (defaults to P).

    Returns (logits at each sequence's last valid position (B, V) f32, cache).
    """
    b, p = tokens.shape
    max_len = cache.k.shape[2]
    cos, sin = rope_table(cfg, max_len)
    x = params["embed"]["tokens"].astype(cfg.dtype)[tokens]
    # honour cfg.attention_impl (flash for long prompts); decode keeps the
    # dense cache path since a single query can't use the blockwise kernel.
    attn_fn = transformer._get_attention_fn(cfg)

    def scan_body(carry, lp):
        x = carry
        q, k, v = transformer.attention_qkv(x, lp, cfg, cos, sin)
        o = attn_fn(q, k, v)
        x = transformer.attention_out(x, o, lp, cfg)
        x = _mlp_apply(x, lp, cfg)
        return x, (k, v)

    x, (ks, vs) = lax.scan(scan_body, x, params["layers"])
    x = rms_norm(x, params["final_norm"]["scale"], cfg.norm_eps)
    if lengths is None:
        lengths = jnp.full((b,), p, jnp.int32)
        x_last = x[:, -1]
    else:
        x_last = x[jnp.arange(b), lengths - 1]
    logits = transformer.unembed(x_last, params, cfg)

    if cfg.kv_cache_dtype == "int8":
        kq, ksc = _kv_quant(ks)
        vq, vsc = _kv_quant(vs)
        return logits, KVCache(
            lax.dynamic_update_slice(cache.k, kq, (0, 0, 0, 0, 0)),
            lax.dynamic_update_slice(cache.v, vq, (0, 0, 0, 0, 0)),
            lengths,
            lax.dynamic_update_slice(cache.k_scale, ksc, (0, 0, 0, 0, 0)),
            lax.dynamic_update_slice(cache.v_scale, vsc, (0, 0, 0, 0, 0)))
    new_k = lax.dynamic_update_slice(cache.k, ks, (0, 0, 0, 0, 0))
    new_v = lax.dynamic_update_slice(cache.v, vs, (0, 0, 0, 0, 0))
    return logits, KVCache(new_k, new_v, lengths)


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------

def decode_step(params, token: jnp.ndarray, cfg: ModelConfig,
                cache: KVCache) -> tuple[jnp.ndarray, KVCache]:
    """One decode step. token: (B,) int32; sequence i sits at position
    cache.length[i] (per-sequence — ragged batches are handled exactly)."""
    max_len = cache.k.shape[2]
    pos = cache.length  # (B,)
    cos, sin = rope_table(cfg, max_len)
    positions = pos[:, None]  # (B, 1)

    x = params["embed"]["tokens"].astype(cfg.dtype)[token[:, None]]  # (B,1,D)

    int8_kv = cfg.kv_cache_dtype == "int8"
    if cfg.decode_attention_impl == "pallas":
        raise ValueError(
            "the contiguous engine's pallas decode kernel was removed (it "
            "measured slower than XLA at every serving shape); "
            "decode_attention_impl='pallas' selects ops.paged_attention "
            "in the paged serving stack (inference.paged_server) instead")
    if cfg.decode_attention_impl != "xla":
        raise ValueError(
            f"unknown decode_attention_impl: {cfg.decode_attention_impl!r}")

    # int8 caches: scales fold into scores/probs inside the op, so the
    # int8 buffers feed the einsums raw — no dequantized HBM copy.
    def attend(q, k_cache, v_cache, k_scale=None, v_scale=None):
        return causal_attention(q, k_cache, v_cache,
                                q_positions=positions,
                                kv_length=cache.length + 1,
                                k_scale=k_scale, v_scale=v_scale)

    # Unrolled layer loop with in-place slice updates. A lax.scan with the
    # cache as stacked ys re-materialises the full (L, B, S, KH, Dh) k/v
    # buffers every token (~1 GB of pure copies per step at the 330M bench
    # config — measured ~5 ms/step of `copy.*` ops on TPU v5e). Unrolling
    # lets XLA chain donated dynamic-update-slices on the same buffers, so
    # per-step cache traffic is just the (B, 1, KH, Dh) writes plus the
    # attention reads.
    k_all, v_all = cache.k, cache.v
    ks_all, vs_all = cache.k_scale, cache.v_scale
    batch_idx = jnp.arange(token.shape[0])
    for layer_idx in range(cfg.num_layers):
        lp = jax.tree.map(lambda w: w[layer_idx], params["layers"])
        q, k, v = transformer.attention_qkv(x, lp, cfg, cos, sin, positions)
        # scatter the new (B, KH, Dh) entries straight into the stacked
        # cache — no read-modify-write of the whole 32MB layer slice
        if int8_kv:
            kq, ksc = _kv_quant(k[:, 0])
            vq, vsc = _kv_quant(v[:, 0])
            k_all = k_all.at[layer_idx, batch_idx, pos].set(kq)
            v_all = v_all.at[layer_idx, batch_idx, pos].set(vq)
            ks_all = ks_all.at[layer_idx, batch_idx, pos].set(ksc)
            vs_all = vs_all.at[layer_idx, batch_idx, pos].set(vsc)
            o = attend(q, k_all[layer_idx], v_all[layer_idx],
                       ks_all[layer_idx], vs_all[layer_idx])
        else:
            k_all = k_all.at[layer_idx, batch_idx, pos].set(k[:, 0])
            v_all = v_all.at[layer_idx, batch_idx, pos].set(v[:, 0])
            o = attend(q, k_all[layer_idx], v_all[layer_idx])
        x = transformer.attention_out(x, o, lp, cfg)
        x = _mlp_apply(x, lp, cfg)
    x = rms_norm(x, params["final_norm"]["scale"], cfg.norm_eps)
    logits = transformer.unembed(x[:, 0], params, cfg)
    return logits, KVCache(k_all, v_all, cache.length + 1, ks_all, vs_all)


def verify_step(params, tokens: jnp.ndarray, cfg: ModelConfig,
                cache: KVCache) -> tuple[jnp.ndarray, KVCache]:
    """Process a (B, K) window of tokens starting at each sequence's
    current cache position in ONE forward pass, returning logits at every
    window position — the target-model half of speculative decoding
    (score K draft tokens for the price of one memory-bound pass).

    Sequence i's window occupies positions [length[i], length[i] + K); its
    kv entries are written into the cache, but `length` is NOT advanced —
    the caller commits however many positions verification accepts (stale
    entries beyond the commit point are masked by `kv_length` and
    overwritten by later writes at the same positions, so rollback is just
    "don't advance").

    Returns (logits (B, K, V) f32, cache with entries written).
    """
    b, kk = tokens.shape
    max_len = cache.k.shape[2]
    cos, sin = rope_table(cfg, max_len)
    pos = cache.length[:, None] + jnp.arange(kk)[None, :]  # (B, K)

    x = params["embed"]["tokens"].astype(cfg.dtype)[tokens]  # (B, K, D)
    int8_kv = cfg.kv_cache_dtype == "int8"
    k_all, v_all = cache.k, cache.v
    ks_all, vs_all = cache.k_scale, cache.v_scale
    batch_idx = jnp.arange(b)
    for layer_idx in range(cfg.num_layers):
        lp = jax.tree.map(lambda w: w[layer_idx], params["layers"])
        q, k, v = transformer.attention_qkv(x, lp, cfg, cos, sin, pos)
        scales = {}
        if int8_kv:
            kq, ksc = _kv_quant(k)
            vq, vsc = _kv_quant(v)
            k_all = k_all.at[layer_idx, batch_idx[:, None], pos].set(kq)
            v_all = v_all.at[layer_idx, batch_idx[:, None], pos].set(vq)
            ks_all = ks_all.at[layer_idx, batch_idx[:, None], pos].set(ksc)
            vs_all = vs_all.at[layer_idx, batch_idx[:, None], pos].set(vsc)
            # scales fold into scores/probs inside the op — no (B, max_len,
            # KH, Dh)-sized dequantized copy per layer per round (that copy
            # used to erase int8's memory win on every speculative round
            # and prefix admission)
            scales = dict(k_scale=ks_all[layer_idx],
                          v_scale=vs_all[layer_idx])
        else:
            k_all = k_all.at[layer_idx, batch_idx[:, None], pos].set(k)
            v_all = v_all.at[layer_idx, batch_idx[:, None], pos].set(v)
        # q_positions give the in-window causal structure; kv_length masks
        # both stale cache entries and the other sequences' longer windows.
        o = causal_attention(q, k_all[layer_idx], v_all[layer_idx],
                             q_positions=pos, kv_length=cache.length + kk,
                             **scales)
        x = transformer.attention_out(x, o, lp, cfg)
        x = _mlp_apply(x, lp, cfg)
    x = rms_norm(x, params["final_norm"]["scale"], cfg.norm_eps)
    logits = transformer.unembed(x, params, cfg)  # (B, K, V)
    return logits, KVCache(k_all, v_all, cache.length, ks_all, vs_all)


# ---------------------------------------------------------------------------
# Embeddings
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("cfg",))
def encode(params, tokens: jnp.ndarray, lengths: jnp.ndarray, *,
           cfg: ModelConfig) -> jnp.ndarray:
    """Sequence embeddings: the decoder run WITHOUT unembedding,
    final-norm hidden states mean-pooled over each sequence's valid
    positions, L2-normalised. tokens: (B, P) right-padded int32;
    lengths: (B,) int32. Returns (B, embed_dim) f32, unit norm.

    Right-padding is exact under causal attention (real positions never
    attend to the trailing pads; pad positions are masked out of the
    pool), so one batched pass serves ragged inputs."""
    b, p = tokens.shape
    cos, sin = rope_table(cfg, p)
    x = params["embed"]["tokens"].astype(cfg.dtype)[tokens]
    attn_fn = transformer._get_attention_fn(cfg)

    def scan_body(x, lp):
        q, k, v = transformer.attention_qkv(x, lp, cfg, cos, sin)
        o = attn_fn(q, k, v)
        x = transformer.attention_out(x, o, lp, cfg)
        x = _mlp_apply(x, lp, cfg)
        return x, None

    x, _ = lax.scan(scan_body, x, params["layers"])
    x = rms_norm(x, params["final_norm"]["scale"], cfg.norm_eps)
    mask = jnp.arange(p)[None, :] < lengths[:, None]
    pooled = (x.astype(jnp.float32) * mask[..., None]).sum(axis=1)
    pooled = pooled / jnp.maximum(lengths[:, None], 1)
    norm = jnp.linalg.norm(pooled, axis=-1, keepdims=True)
    return pooled / jnp.maximum(norm, 1e-9)


# ---------------------------------------------------------------------------
# Generate
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("cfg", "infer_cfg", "max_len"))
def generate(params, prompt: jnp.ndarray, rng: jax.Array, *,
             cfg: ModelConfig, infer_cfg: InferConfig,
             max_len: int | None = None,
             prompt_lengths: jnp.ndarray | None = None) -> jnp.ndarray:
    """Batched generation. prompt: (B, P) int32, right-padded when ragged
    (pass prompt_lengths (B,) for the true lengths).

    Returns (B, max_decode_len) int32. Sequences that hit eos_token_id emit
    pad_token_id afterwards. Runs exactly max_decode_len - 1 decode steps:
    the first token is sampled from prefill logits and the last sampled
    token is never fed back through the model.
    """
    b, p = prompt.shape
    n_new = infer_cfg.max_decode_len
    max_len = max_len or (p + n_new)
    if max_len < p + n_new:
        raise ValueError(
            f"max_len={max_len} < prompt ({p}) + max_decode_len ({n_new}); "
            "the cache would silently wrap")
    cache = init_cache(cfg, b, max_len)
    logits, cache = prefill(params, prompt, cfg, cache, prompt_lengths)

    def step(carry, rng_t):
        logits, cache, done = carry
        tok = sample_logits(logits, rng_t, infer_cfg)
        tok = jnp.where(done, infer_cfg.pad_token_id, tok)
        done = jnp.logical_or(done, tok == infer_cfg.eos_token_id)
        logits, cache = decode_step(params, tok, cfg, cache)
        return (logits, cache, done), tok

    rngs = jax.random.split(rng, n_new)
    done0 = jnp.zeros((b,), bool)
    (logits, _, done), tokens = lax.scan(
        step, (logits, cache, done0), rngs[:-1])
    last = sample_logits(logits, rngs[-1], infer_cfg)
    last = jnp.where(done, infer_cfg.pad_token_id, last)
    tokens = jnp.concatenate([tokens, last[None]], axis=0)
    return tokens.T  # (B, n_new)

"""Autoregressive inference engine: KV cache, prefill + decode, generate.

TPU-first shape discipline: the cache is a static (L, B, max_len, KH, Dh)
buffer; prefill fills the prompt region in one batched pass (full MXU
utilisation), then decode steps run S=1 attention against the cache under a
single `lax.scan` inside one jit — no per-token dispatch, no dynamic
shapes, no host round-trips. Sampling (greedy/temp/top-k/top-p) happens
on-device between steps; finished sequences keep "generating" pad tokens so
shapes stay static (standard SPMD practice).

Sharding: cache heads ride the same `tp` axis as attention weights; batch
rides (dp, fsdp). `generate` is jit-compatible and can be wrapped with
shardings by the serving layer.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from cloud_server_tpu.config import InferConfig, ModelConfig
from cloud_server_tpu.inference.sampling import sample_logits
from cloud_server_tpu.models import transformer
from cloud_server_tpu.ops import apply_rope, causal_attention, rms_norm, rope_frequencies
from cloud_server_tpu.ops.activations import swiglu


class KVCache(NamedTuple):
    k: jnp.ndarray  # (L, B, max_len, KH, Dh)
    v: jnp.ndarray  # (L, B, max_len, KH, Dh)
    length: jnp.ndarray  # (B,) int32 — valid entries per sequence


def init_cache(cfg: ModelConfig, batch: int, max_len: int) -> KVCache:
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
    dtype = jnp.dtype(cfg.dtype)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                   length=jnp.zeros((batch,), jnp.int32))


# ---------------------------------------------------------------------------
# Prefill
# ---------------------------------------------------------------------------

def prefill(params, tokens: jnp.ndarray, cfg: ModelConfig,
            cache: KVCache) -> tuple[jnp.ndarray, KVCache]:
    """Run the prompt (B, P) through the model, populating cache[:, :, :P].

    Returns (logits at the last prompt position (B, V) f32, cache).
    """
    b, p = tokens.shape
    max_len = cache.k.shape[2]
    cos, sin = rope_frequencies(cfg.head_dim, max_len, cfg.rope_theta)
    x = params["embed"]["tokens"].astype(cfg.dtype)[tokens]
    # honour cfg.attention_impl (flash for long prompts); decode keeps the
    # dense cache path since a single query can't use the blockwise kernel.
    attn_fn = transformer._get_attention_fn(cfg)

    def scan_body(carry, lp):
        x = carry
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"].astype(cfg.dtype))
        k = jnp.einsum("bsd,dhk->bshk", h, lp["wk"].astype(cfg.dtype))
        v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"].astype(cfg.dtype))
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        o = attn_fn(q, k, v)
        x = x + jnp.einsum("bshk,hkd->bsd", o, lp["wo"].astype(cfg.dtype))
        x = transformer._mlp_block(x, lp, cfg)
        return x, (k, v)

    x, (ks, vs) = lax.scan(scan_body, x, params["layers"])
    x = rms_norm(x, params["final_norm"]["scale"], cfg.norm_eps)
    head = (params["embed"]["tokens"].T if cfg.tie_embeddings
            else params["lm_head"]["kernel"])
    logits = jnp.einsum("bd,dv->bv", x[:, -1], head.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    logits = transformer.apply_logits_softcap(logits, cfg)

    new_k = lax.dynamic_update_slice(cache.k, ks, (0, 0, 0, 0, 0))
    new_v = lax.dynamic_update_slice(cache.v, vs, (0, 0, 0, 0, 0))
    length = jnp.full((b,), p, jnp.int32)
    return logits, KVCache(new_k, new_v, length)


# ---------------------------------------------------------------------------
# Decode
# ---------------------------------------------------------------------------

def decode_step(params, token: jnp.ndarray, cfg: ModelConfig,
                cache: KVCache) -> tuple[jnp.ndarray, KVCache]:
    """One decode step. token: (B,) int32 at position cache.length.

    Assumes uniform position across the batch (cache.length[0]); ragged
    batches left-pad prompts to equal length.
    """
    b = token.shape[0]
    max_len = cache.k.shape[2]
    pos = cache.length[0]
    cos, sin = rope_frequencies(cfg.head_dim, max_len, cfg.rope_theta)
    positions = jnp.broadcast_to(pos, (b, 1))

    x = params["embed"]["tokens"].astype(cfg.dtype)[token[:, None]]  # (B,1,D)

    def scan_body(carry, layer):
        x = carry
        lp, k_cache, v_cache = layer
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"].astype(cfg.dtype))
        k = jnp.einsum("bsd,dhk->bshk", h, lp["wk"].astype(cfg.dtype))
        v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"].astype(cfg.dtype))
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
        k_cache = lax.dynamic_update_slice(k_cache, k, (0, pos, 0, 0))
        v_cache = lax.dynamic_update_slice(v_cache, v, (0, pos, 0, 0))
        o = causal_attention(
            q, k_cache, v_cache,
            q_positions=positions,
            kv_length=cache.length + 1)
        x = x + jnp.einsum("bshk,hkd->bsd", o, lp["wo"].astype(cfg.dtype))
        x = transformer._mlp_block(x, lp, cfg)
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = lax.scan(
        scan_body, x, (params["layers"], cache.k, cache.v))
    x = rms_norm(x, params["final_norm"]["scale"], cfg.norm_eps)
    head = (params["embed"]["tokens"].T if cfg.tie_embeddings
            else params["lm_head"]["kernel"])
    logits = jnp.einsum("bd,dv->bv", x[:, 0], head.astype(cfg.dtype),
                        preferred_element_type=jnp.float32)
    logits = transformer.apply_logits_softcap(logits, cfg)
    return logits, KVCache(new_k, new_v, cache.length + 1)


# ---------------------------------------------------------------------------
# Generate
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("cfg", "infer_cfg", "max_len"))
def generate(params, prompt: jnp.ndarray, rng: jax.Array, *,
             cfg: ModelConfig, infer_cfg: InferConfig,
             max_len: int | None = None) -> jnp.ndarray:
    """Batched generation. prompt: (B, P) int32 (equal-length prompts).

    Returns (B, max_decode_len) int32. Sequences that hit eos_token_id emit
    pad_token_id afterwards.
    """
    b, p = prompt.shape
    n_new = infer_cfg.max_decode_len
    max_len = max_len or (p + n_new)
    if max_len < p + n_new:
        raise ValueError(
            f"max_len={max_len} < prompt ({p}) + max_decode_len ({n_new}); "
            "the cache would silently wrap")
    cache = init_cache(cfg, b, max_len)
    logits, cache = prefill(params, prompt, cfg, cache)

    def step(carry, rng_t):
        logits, cache, done = carry
        tok = sample_logits(logits, rng_t, infer_cfg)
        tok = jnp.where(done, infer_cfg.pad_token_id, tok)
        done = jnp.logical_or(done, tok == infer_cfg.eos_token_id)
        logits, cache = decode_step(params, tok, cfg, cache)
        return (logits, cache, done), tok

    rngs = jax.random.split(rng, n_new)
    done0 = jnp.zeros((b,), bool)
    (_, _, _), tokens = lax.scan(step, (logits, cache, done0), rngs)
    return tokens.T  # (B, n_new)

"""Paged inference engine: the window-forward primitive over a page pool.

Where `inference.engine` owns a contiguous (L, B, max_len, KH, Dh) cache,
this module owns the PAGED cache: one global pool of fixed-size pages per
layer plus per-slot page tables, so device memory scales with the tokens
actually resident (not max_slots x max_len) and pages can be SHARED
between slots (refcounted prefix reuse — inference/block_allocator.py).

Everything the paged server dispatches is one primitive,
`window_forward(tokens (B, W))`: embed W new positions per slot at
absolute positions [lengths, lengths + W), write their kv into the pool
through the page table, and attend each window row against the slot's
whole paged history (pallas kernel `ops.paged_attention` on TPU, gather +
dense XLA elsewhere). The server's flows are just widths:

  * plain decode             W = 1
  * speculative verification W = drafts + 1   (logits="all")
  * prefill / chunked prefill / prefix-cache continuation: W = chunk,
    with per-slot start offsets carried by `lengths` (a slot resuming
    after `n` shared-prefix tokens simply starts at lengths=n)
  * MIXED batch (stall-free scheduling): per-row `widths` — decode rows
    (width 1 or drafts+1) and prefill-chunk rows (width chunk) share ONE
    ragged dispatch; writes past a row's width drop, attention anchors
    each row at its own width (ops.paged_attention ragged rule)

`window_forward` does NOT advance `lengths` — the caller commits however
many window positions survive (sampling, speculative acceptance), exactly
like `engine.verify_step`: stale entries past the commit point are masked
by `lengths` and overwritten by later writes at the same positions.

Write discipline and sharing safety: a write at absolute position p goes
to page `tables[b, p // ps]`, offset `p % ps`. The allocator guarantees
shared (refcount > 1 or cached) pages only ever cover positions < every
sharing slot's private start, and all writes happen at positions >=
lengths >= private start — so shared pages are immutable by
construction. Freed slots get sentinel tables (page id == num_pages):
their writes drop (`mode="drop"`), which is what makes it safe to keep
dispatching the full slot batch while some slots are empty.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from cloud_server_tpu.config import ModelConfig
from cloud_server_tpu.inference import multi_lora
from cloud_server_tpu.inference.engine import _kv_quant, _mlp_apply
from cloud_server_tpu.models import transformer
from cloud_server_tpu.ops import rms_norm, rope_table
from cloud_server_tpu.ops.paged_attention import (
    paged_attention, paged_attention_tp, paged_attention_xla)


class PagedKVCache(NamedTuple):
    """Page pool + per-slot view. One pool serves every slot and layer."""

    k: jnp.ndarray        # (L, num_pages, KH, Dh, ps) cfg.dtype | int8
    v: jnp.ndarray        # (L, num_pages, KH, Dh, ps) — transposed pages
    #                       (positions on lanes; see ops/paged_attention)
    lengths: jnp.ndarray  # (B,) int32 — committed kv entries per slot
    tables: jnp.ndarray   # (B, max_pages_per_slot) int32; num_pages = free
    k_scale: jnp.ndarray | None = None  # (L, num_pages, KH, ps) f32
    v_scale: jnp.ndarray | None = None

    @property
    def page_size(self) -> int:
        return self.k.shape[4]

    @property
    def num_pages(self) -> int:
        return self.k.shape[1]

    @property
    def max_context(self) -> int:
        return self.tables.shape[1] * self.page_size


def init_paged_cache(cfg: ModelConfig, *, num_pages: int, page_size: int,
                     batch: int, max_pages_per_slot: int) -> PagedKVCache:
    """Zeroed pool; all tables at the sentinel (num_pages = "no page")."""
    shape = (cfg.num_layers, num_pages, cfg.num_kv_heads, cfg.head_dim,
             page_size)
    tables = jnp.full((batch, max_pages_per_slot), num_pages, jnp.int32)
    lengths = jnp.zeros((batch,), jnp.int32)
    if cfg.kv_cache_dtype == "int8":
        sshape = shape[:3] + (page_size,)
        return PagedKVCache(
            k=jnp.zeros(shape, jnp.int8), v=jnp.zeros(shape, jnp.int8),
            lengths=lengths, tables=tables,
            k_scale=jnp.zeros(sshape, jnp.float32),
            v_scale=jnp.zeros(sshape, jnp.float32))
    if cfg.kv_cache_dtype != "model":
        raise ValueError(f"unknown kv_cache_dtype: {cfg.kv_cache_dtype!r}")
    dtype = jnp.dtype(cfg.dtype)
    return PagedKVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
                        lengths=lengths, tables=tables)


def quantize_pool(pool: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """int8-quantize a TRANSPOSED page pool (L, P, KH, Dh, ps): absmax
    over Dh (axis 3) — the same per-(position, head) granularity
    `_write_window` stores via `engine._kv_quant`. Single source of truth
    for tests and benches building pools wholesale.

    Returns (int8 pool, (L, P, KH, ps) f32 scales)."""
    sc = jnp.maximum(
        jnp.max(jnp.abs(pool.astype(jnp.float32)), axis=3,
                keepdims=True) / 127.0, 1e-8)
    q = jnp.round(pool.astype(jnp.float32) / sc).astype(jnp.int8)
    return q, sc[:, :, :, 0, :]


def hbm_bytes(cache: PagedKVCache) -> int:
    """Device bytes held by the pool (the capacity comparison the paged
    layout exists to win — see tests/test_paged_server.py)."""
    n = cache.k.size * cache.k.dtype.itemsize * 2
    if cache.k_scale is not None:
        n += cache.k_scale.size * 4 * 2
    return n


def _write_window(cache: PagedKVCache, layer: int, k, v, pos):
    """Write fresh (B, W, KH, Dh) k/v at absolute positions (B, W)
    through the page table. Out-of-chain positions (sentinel table
    entries) drop.

    Implementation note: a direct elementwise scatter into the transposed
    (.., Dh, ps) pages would write 2-byte elements at stride ps — an XLA
    scatter slow path that dominated the decode step when measured. So
    writes go page-at-a-time instead: GATHER each touched page (a W-token
    window touches at most 2 consecutive pages per slot — both
    slot-PRIVATE by the allocator's sharing invariant, so whole-page
    read-modify-write races nothing), merge the window's positions in
    with a one-hot lane mask, and SET the whole page back — one
    single-index scatter of contiguous page blocks, ~2 pages of traffic
    per slot per layer instead of thousands of strided element writes."""
    ps = cache.page_size
    b, w = pos.shape
    max_slot = cache.tables.shape[1] - 1
    int8 = cache.k_scale is not None
    if int8:
        kq, ksc = _kv_quant(k)
        vq, vsc = _kv_quant(v)
        k_src = kq.astype(cache.k.dtype)
        v_src = vq.astype(cache.v.dtype)
    else:
        k_src = k.astype(cache.k.dtype)
        v_src = v.astype(cache.v.dtype)

    new = {"k": cache.k, "v": cache.v,
           "k_scale": cache.k_scale, "v_scale": cache.v_scale}
    # a W-token window starting mid-page touches ceil(W/ps)+1 consecutive
    # page slots; W=1 touches exactly one
    n_groups = 1 if w == 1 else (-(-w // ps) + 1)
    first_slot = jnp.clip(pos[:, 0] // ps, 0, max_slot)  # (B,)
    lane = jnp.arange(ps)
    for g in range(n_groups):
        slot_g = jnp.clip(first_slot + g, 0, max_slot)
        page_g = jnp.take_along_axis(cache.tables, slot_g[:, None],
                                     axis=1)[:, 0]          # (B,)
        in_page = (pos // ps) == slot_g[:, None]            # (B, W)
        # one-hot over lanes for each window position in this page
        oh = (in_page[:, :, None]
              & (lane[None, None, :] == (pos % ps)[:, :, None]))  # (B,W,ps)
        ohf = oh.astype(jnp.float32)
        any_write = ohf.sum(axis=1)                          # (B, ps)
        for name, src in (("k", k_src), ("v", v_src)):
            pool = new[name]
            pages_old = pool[layer, jnp.clip(page_g, 0, pool.shape[1] - 1)]
            upd = jnp.einsum("bwhd,bwp->bhdp",
                             src.astype(jnp.float32), ohf)
            merged = (pages_old.astype(jnp.float32)
                      * (1.0 - any_write[:, None, None, :]) + upd)
            new[name] = pool.at[layer, page_g].set(
                merged.astype(pool.dtype), mode="drop")
        if int8:
            for name, sc in (("k_scale", ksc), ("v_scale", vsc)):
                spool = new[name]
                sp_old = spool[layer, jnp.clip(page_g, 0,
                                               spool.shape[1] - 1)]
                upd = jnp.einsum("bwh,bwp->bhp", sc[..., 0], ohf)
                merged = sp_old * (1.0 - any_write[:, None, :]) + upd
                new[name] = spool.at[layer, page_g].set(merged,
                                                        mode="drop")
    return cache._replace(k=new["k"], v=new["v"],
                          k_scale=new["k_scale"], v_scale=new["v_scale"])


# Widest window the pallas path serves. Thin windows (<= 32) take the
# batch-unrolled kernel with its cross-slot DMA chain; wider windows
# (prefill chunks) dispatch the grid-over-(slot, head) wide kernel
# (ops.paged_attention._paged_attention_wide) — length-bounded page
# reads instead of the XLA path's full-padded-cache gather per layer
# per chunk. Beyond this cap (wider than any prefill chunk the server
# issues) the XLA gather path remains the fallback.
_PALLAS_MAX_W = 256


def window_forward(params, tokens: jnp.ndarray, cfg: ModelConfig,
                   cache: PagedKVCache, *, logits_at: jnp.ndarray | None,
                   all_logits: bool = False,
                   pages_per_block: int | None = None,
                   mesh=None, tp_axis: str = "tp",
                   lora=None, aid=None, widths: jnp.ndarray | None = None):
    """Forward W new positions per slot against the paged cache.

    Args:
      tokens: (B, W) int32 — slot b's tokens for absolute positions
        [lengths[b], lengths[b] + W). Pad rows/slots freely: writes
        through sentinel tables drop, outputs are masked by the caller.
      logits_at: (B,) int32 in-window indices — return logits only at
        that position per slot ((B, V) f32); the chunked-prefill path
        needs one sampled position per chunk, never the (B, W, V) tensor.
      all_logits: return (B, W, V) f32 (speculative verification).
        With neither, returns None (interior prefill chunks).
      widths: optional (B,) int32 — per-row VALID window widths for
        ragged mixed batches. Positions at window index >= widths[b]
        neither write kv nor anchor attention: their writes drop (the
        page-table scatter masks them) and attention treats row b's
        window as [lengths[b], lengths[b] + widths[b]) exactly as a
        width-widths[b] uniform dispatch would. Rows with width 0 are
        fully inert (sentinel-table discipline still applies on top).
      lora, aid: multi-adapter serving — (stacks, scales) from
        inference.multi_lora.AdapterSet.device_args + per-slot adapter
        ids (B,); each layer gathers its per-row (a, b, scale) and the
        transformer blocks add the low-rank deltas (id 0 = exact base).
      mesh, tp_axis: tensor-parallel serving. The XLA parts (matmuls,
        gathers, unembed) need nothing — params carry NamedShardings and
        jit propagates them, as in the contiguous engine. Only the
        pallas kernel cannot be auto-partitioned; with a mesh whose
        `tp_axis` is > 1 it runs under shard_map with kv heads sharded
        (ops.paged_attention.paged_attention_tp).

    Returns (logits, cache') — cache' has the window written but lengths
    UNCHANGED (see module docstring).
    """
    b, w = tokens.shape
    pos = cache.lengths[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
    # ragged rows: positions past a row's width write nowhere (pos -1
    # never matches a page slot in _write_window, so the page merge is an
    # identity rewrite of the row's own private pages — shared pages are
    # never touched because writes start at lengths >= private start)
    wpos = pos if widths is None else jnp.where(
        jnp.arange(w, dtype=jnp.int32)[None, :] < widths[:, None], pos, -1)
    cos, sin = rope_table(cfg, cache.max_context)
    x = params["embed"]["tokens"].astype(cfg.dtype)[tokens]  # (B, W, D)

    use_pallas = (cfg.decode_attention_impl == "pallas"
                  and w <= _PALLAS_MAX_W)
    if pages_per_block is None:
        # wider windows leave less VMEM for the double-buffered page
        # blocks; 8 pages measured fastest at W=1 on v5e
        pages_per_block = 8 if w <= 8 else 4
    lens_after = cache.lengths + (w if widths is None else widths)

    for layer_idx in range(cfg.num_layers):
        lp = jax.tree.map(lambda p: p[layer_idx], params["layers"])
        ll = (None if lora is None
              else multi_lora.layer_lora(lora, aid, layer_idx))
        q, k, v = transformer.attention_qkv(x, lp, cfg, cos, sin, pos,
                                            lora=ll)
        cache = _write_window(cache, layer_idx, k, v, wpos)
        if use_pallas:
            if mesh is not None and mesh.shape.get(tp_axis, 1) > 1:
                o = paged_attention_tp(
                    q, cache.k, cache.v, lens_after, cache.tables,
                    layer_idx, mesh=mesh, axis_name=tp_axis,
                    pages_per_block=pages_per_block,
                    k_scale_pool=cache.k_scale, v_scale_pool=cache.v_scale,
                    widths=widths)
            else:
                o = paged_attention(
                    q, cache.k, cache.v, lens_after, cache.tables,
                    layer_idx, pages_per_block=pages_per_block,
                    k_scale_pool=cache.k_scale, v_scale_pool=cache.v_scale,
                    widths=widths)
        else:
            o = paged_attention_xla(
                q, cache.k, cache.v, lens_after, cache.tables, layer_idx,
                k_scale_pool=cache.k_scale, v_scale_pool=cache.v_scale,
                widths=widths)
        x = transformer.attention_out(x, o, lp, cfg, lora=ll)
        x = _mlp_apply(x, lp, cfg, lora=ll)

    x = rms_norm(x, params["final_norm"]["scale"], cfg.norm_eps)
    if all_logits:
        return transformer.unembed(x, params, cfg), cache
    if logits_at is not None:
        x_sel = x[jnp.arange(b), jnp.clip(logits_at, 0, w - 1)]  # (B, D)
        return transformer.unembed(x_sel, params, cfg), cache
    return None, cache

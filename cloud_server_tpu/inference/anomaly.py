"""Online anomaly watchdog: the stack notices its own incidents.

Every prior observability layer is *pull*-shaped — histograms, burn
rates, span trees, flight records all wait for an operator to scrape
them, and the bounded rings scroll the evidence away while nobody is
looking. This module is the push half: a small rule engine, fed only
from host state the schedulers already own, that latches "something
is wrong" windows, counts them, and lets the servers react (retain
the tail trace, auto-capture a forensic bundle, arm a scheduler
capture) at the moment the anomaly is live rather than after the
fact.

Design rules (the `faults.OverloadDetector` discipline):

  * **Zero new device work, zero new clock reads on the hot path.**
    `observe_iteration` folds signals `_record_iteration` already
    computed; `observe_request` folds latencies `_complete` already
    derived; both take the caller's `now`. The module is stdlib-only
    (DD3 jax-free roster), the observe paths are on the hot-path
    lint roster, and the single leaf lock is lock-discipline
    audited.
  * **Hysteresis, not flapping.** A rule ACTIVATES the moment its
    condition crosses (after a warm-up so cold EWMAs cannot fire)
    and DEACTIVATES only after `hold_s` of continuous recovery — the
    `OverloadDetector` level-latch shape. Each activation edge
    increments `fired_total[rule]` once and appends one event to a
    bounded ring; the open event's `end` is stamped at deactivation.
  * **No configuration, no cost.** `resolve_anomaly` returns None
    for an empty config; every server call site is guarded, so the
    unconfigured serving path is byte-identical.

Rule catalog (`RULES` is the closed set — metric label values and
the docs table key off it):

    slo_burn        multi-window burn-rate page: some class/metric
                    burns error budget over `fast_burn` in the
                    SHORTEST configured SLO window AND over
                    `slow_burn` in the LONGEST (SRE Workbook rule).
    latency_shift   TTFT or ITL fast-EWMA rose `factor`x above its
                    own slow-EWMA rolling baseline (and above
                    `min_s` absolute).
    cache_collapse  prefix-cache hit-rate fast-EWMA fell below
                    `frac` of its slow-EWMA baseline.
    breaker_flap    overload/breaker level changed >= `flaps` times
                    inside `window_s` (admission flapping open/shut).
    deadline_spike  >= `count` deadline-expired finishes inside
                    `window_s`.
    preempt_spike   >= `count` preemption-requeues inside
                    `window_s`.
    host_gap        per-iteration `host_gap_frac` fast-EWMA rose
                    `factor`x above its slow baseline (and above
                    `min_frac`) — the scheduler is starving the
                    device on host work.
    wedged          requests are pending but no scheduler iteration
                    has been observed for `stall_s` (graded lazily
                    on the read path — a wedged scheduler cannot
                    grade itself).

Config JSON shape (`InferConfig.anomaly_config`, server `anomaly=`,
CLI `--anomaly-config`; a JSON object, a JSON string, or a file
path)::

    {"hold_s": 5.0, "warmup": 32, "check_every": 16,
     "event_capacity": 64, "alpha_fast": 0.3, "alpha_slow": 0.02,
     "capture_iters": 0, "capture_dir": "",
     "disable": ["cache_collapse"],
     "rules": {"deadline_spike": {"count": 5, "window_s": 10.0}}}

`capture_iters`/`capture_dir` arm the existing `POST /debug/trace`
machinery for N iterations on an activation edge (off unless both
set); the `bundle_on_anomaly` knob (InferConfig) makes the servers
snapshot a forensic bundle on the same edge.
"""

from __future__ import annotations

import collections
import threading
import time

from cloud_server_tpu.inference.faults import _resolve_config

# The closed rule set: `anomaly_active{rule=}` / `anomalies_total
# {rule=}` label values, the docs rule-catalog rows, and the /stats
# block all key off this tuple. Adding a rule is a reviewed decision
# that must update all three.
RULES = ("slo_burn", "latency_shift", "cache_collapse",
         "breaker_flap", "deadline_spike", "preempt_spike",
         "host_gap", "wedged")

_RULE_DEFAULTS: dict[str, dict[str, float]] = {
    "slo_burn": {"fast_burn": 14.4, "slow_burn": 6.0},
    "latency_shift": {"factor": 3.0, "min_s": 0.05},
    "cache_collapse": {"frac": 0.5, "min_baseline": 0.2},
    "breaker_flap": {"flaps": 4.0, "window_s": 30.0},
    "deadline_spike": {"count": 3.0, "window_s": 10.0},
    "preempt_spike": {"count": 8.0, "window_s": 10.0},
    "host_gap": {"factor": 2.0, "min_frac": 0.2},
    "wedged": {"stall_s": 10.0},
}


class AnomalyWatchdog:
    """Rule engine over per-iteration and per-finish host signals.

    `observe_iteration` runs once per busy scheduler iteration;
    `observe_request` once per request finish; `active_count` once
    per finish (the tail-retention predicate's "inside an open
    anomaly window" clause). All three are hot-path rostered: plain
    float math under one small lock, no clock reads (callers pass
    the perf_counter moment they already had). Everything else —
    `stats`, `events`, `active` — is scrape-path only.

    Both observe methods return a tuple of rules that ACTIVATED on
    this call (empty almost always), so the scheduler can trigger
    auto-capture exactly on the edge without polling."""

    def __init__(self, config: dict | None = None, *,
                 clock=time.perf_counter):
        cfg = dict(config or {})
        self._clock = clock
        self.hold_s = float(cfg.pop("hold_s", 5.0))
        self.warmup = int(cfg.pop("warmup", 32))
        self.check_every = int(cfg.pop("check_every", 16))
        self.event_capacity = int(cfg.pop("event_capacity", 64))
        self.alpha_fast = float(cfg.pop("alpha_fast", 0.3))
        self.alpha_slow = float(cfg.pop("alpha_slow", 0.02))
        self.capture_iters = int(cfg.pop("capture_iters", 0))
        self.capture_dir = str(cfg.pop("capture_dir", ""))
        if self.hold_s < 0:
            raise ValueError("anomaly hold_s must be >= 0")
        if self.check_every <= 0 or self.event_capacity <= 0:
            raise ValueError(
                "anomaly check_every / event_capacity must be positive")
        for name, a in (("alpha_fast", self.alpha_fast),
                        ("alpha_slow", self.alpha_slow)):
            if not 0.0 < a <= 1.0:
                raise ValueError(f"anomaly {name} must be in (0, 1]")
        disabled = cfg.pop("disable", ())
        self._enabled = {r: True for r in RULES}
        for r in disabled:
            if r not in self._enabled:
                raise ValueError(f"unknown anomaly rule to disable: {r!r}")
            self._enabled[r] = False
        self._th: dict[str, dict[str, float]] = {
            r: dict(d) for r, d in _RULE_DEFAULTS.items()}
        for r, spec in dict(cfg.pop("rules", {})).items():
            if r not in self._th:
                raise ValueError(f"unknown anomaly rule: {r!r}")
            for k, v in dict(spec).items():
                if k not in self._th[r]:
                    raise ValueError(
                        f"unknown anomaly threshold {r}.{k}")
                self._th[r][k] = float(v)
        if cfg:
            raise ValueError(f"unknown anomaly config keys: {sorted(cfg)}")

        self._lock = threading.Lock()
        self._slo = None  # bound post-construction (bind_slo)
        # fast/slow EWMA pairs per shifted signal; None until primed
        self._ew: dict[str, list] = {
            s: [None, None] for s in ("ttft", "itl", "cache_hit",
                                      "host_gap")}
        self._n_iter = 0
        self._n_req = 0
        # windowed event timestamps (pruned against each rule's own
        # window on the observe that reads them — bounded by prune)
        self._flap_ts: collections.deque = collections.deque()
        self._deadline_ts: collections.deque = collections.deque()
        self._preempt: collections.deque = collections.deque()  # (ts, n)
        self._preempt_sum = 0
        self._last_level: int | None = None
        self._last_iter_ts: float | None = None
        self._last_pending = 0
        # rule -> open-event dict (also referenced from the ring)
        self._open: dict[str, dict] = {}
        # rule -> last moment its condition held (hysteresis clock)
        self._last_true: dict[str, float] = {}
        self._events: collections.deque = collections.deque(
            maxlen=self.event_capacity)
        self.fired_total: dict[str, int] = {r: 0 for r in RULES}

    def bind_slo(self, tracker) -> None:
        """Attach the server's SLOTracker (or None) so `slo_burn` can
        sample burn rates every `check_every` iterations."""
        self._slo = tracker

    # -- hot path -----------------------------------------------------------

    def _update_rule(self, rule: str, firing: bool, now: float,
                     details: dict, fired: list) -> None:
        """One rule's activate/hold/deactivate step (called with the
        lock held). Activation is immediate; deactivation waits for
        `hold_s` of continuous recovery."""
        if firing:
            self._last_true[rule] = now
            if rule not in self._open:
                ev = {"rule": rule, "start": now, "end": None,
                      "details": details}
                self._open[rule] = ev
                self._events.append(ev)
                self.fired_total[rule] += 1
                fired.append(rule)
        elif rule in self._open:
            if now - self._last_true.get(rule, now) >= self.hold_s:
                self._open.pop(rule)["end"] = now

    def _shift(self, signal: str, value: float) -> tuple[float, float]:
        """Fold `value` into the signal's fast/slow EWMA pair; returns
        the updated (fast, slow)."""
        pair = self._ew[signal]
        if pair[0] is None:
            pair[0] = pair[1] = value
        else:
            pair[0] += self.alpha_fast * (value - pair[0])
            pair[1] += self.alpha_slow * (value - pair[1])
        return pair[0], pair[1]

    def observe_iteration(self, *, now: float, host_gap_frac: float = 0.0,
                          pending: int = 0, preempt_delta: int = 0,
                          cache_lookup_delta: int = 0,
                          cache_hit_delta: int = 0,
                          overload_level: int = 0) -> tuple:
        """Fold one busy iteration's signals; returns the rules that
        activated on this call. All inputs are numbers the scheduler's
        `_record_iteration` already computed for the flight record —
        no measurement of its own, no clock read."""
        burn = None
        # analysis: allow[lock-discipline] scheduler-thread-only
        # counter read: burn_rates takes the SLO tracker's own leaf
        # lock, so it must be sampled BEFORE this watchdog's lock
        # (no nested acquisition); observe_iteration has exactly one
        # caller thread, so the unlocked read cannot race
        n_iter = self._n_iter
        if (self._slo is not None and self._enabled["slo_burn"]
                and n_iter % self.check_every == 0):
            burn = self._slo.burn_rates(now)
        fired: list = []
        with self._lock:
            self._n_iter += 1
            self._last_iter_ts = now
            self._last_pending = pending
            warm = self._n_iter >= self.warmup

            if self._enabled["wedged"] and "wedged" in self._open:
                # an observed iteration is the proof of un-wedging:
                # close immediately, no hold (the stall IS over)
                self._last_true.pop("wedged", None)
                self._open.pop("wedged")["end"] = now

            if self._enabled["host_gap"]:
                fast, slow = self._shift("host_gap", host_gap_frac)
                th = self._th["host_gap"]
                firing = (warm and fast > th["min_frac"]
                          and fast > th["factor"] * slow)
                self._update_rule("host_gap", firing, now,
                                  {"fast": fast, "slow": slow}, fired)

            if self._enabled["cache_collapse"] and cache_lookup_delta > 0:
                rate = cache_hit_delta / cache_lookup_delta
                fast, slow = self._shift("cache_hit", rate)
                th = self._th["cache_collapse"]
                firing = (warm and slow > th["min_baseline"]
                          and fast < th["frac"] * slow)
                self._update_rule("cache_collapse", firing, now,
                                  {"fast": fast, "slow": slow}, fired)

            if self._enabled["preempt_spike"]:
                th = self._th["preempt_spike"]
                if preempt_delta > 0:
                    self._preempt.append((now, preempt_delta))
                    self._preempt_sum += preempt_delta
                lo = now - th["window_s"]
                while self._preempt and self._preempt[0][0] < lo:
                    self._preempt_sum -= self._preempt.popleft()[1]
                firing = self._preempt_sum >= th["count"]
                self._update_rule("preempt_spike", firing, now,
                                  {"count": self._preempt_sum}, fired)

            if self._enabled["breaker_flap"]:
                th = self._th["breaker_flap"]
                if (self._last_level is not None
                        and overload_level != self._last_level):
                    self._flap_ts.append(now)
                self._last_level = overload_level
                lo = now - th["window_s"]
                while self._flap_ts and self._flap_ts[0] < lo:
                    self._flap_ts.popleft()
                firing = len(self._flap_ts) >= th["flaps"]
                self._update_rule("breaker_flap", firing, now,
                                  {"flaps": len(self._flap_ts)}, fired)

            if burn is not None:
                th = self._th["slo_burn"]
                worst = None
                for cls, metrics in burn.items():
                    for metric, (fast_b, slow_b) in metrics.items():
                        if (fast_b >= th["fast_burn"]
                                and slow_b >= th["slow_burn"]):
                            if worst is None or fast_b > worst[2]:
                                worst = (cls, metric, fast_b, slow_b)
                self._update_rule(
                    "slo_burn", worst is not None, now,
                    {} if worst is None else
                    {"class": worst[0], "metric": worst[1],
                     "fast_burn": worst[2], "slow_burn": worst[3]},
                    fired)
        return tuple(fired)

    def observe_request(self, *, now: float, ttft_s=None, itl_s=None,
                        finish_reason=None) -> tuple:
        """Fold one finished request's latencies and terminal state;
        returns the rules that activated on this call. Called from
        `_complete` with timestamps the request already carries."""
        fired: list = []
        with self._lock:
            self._n_req += 1
            warm = self._n_req >= self.warmup

            if self._enabled["latency_shift"]:
                th = self._th["latency_shift"]
                firing = False
                details: dict = {}
                for name, value in (("ttft", ttft_s), ("itl", itl_s)):
                    if value is None:
                        continue
                    fast, slow = self._shift(name, value)
                    if (warm and fast > th["min_s"]
                            and fast > th["factor"] * slow):
                        firing = True
                        details = {"metric": name, "fast": fast,
                                   "slow": slow}
                self._update_rule("latency_shift", firing, now,
                                  details, fired)

            if self._enabled["deadline_spike"]:
                th = self._th["deadline_spike"]
                if finish_reason == "deadline":
                    self._deadline_ts.append(now)
                lo = now - th["window_s"]
                while self._deadline_ts and self._deadline_ts[0] < lo:
                    self._deadline_ts.popleft()
                firing = len(self._deadline_ts) >= th["count"]
                self._update_rule("deadline_spike", firing, now,
                                  {"count": len(self._deadline_ts)},
                                  fired)
        return tuple(fired)

    def active_count(self, now: float | None = None) -> int:
        """Number of currently-open anomaly windows (the tail
        retention predicate's cheap per-finish read; one lock, no
        clock read when `now` is passed)."""
        with self._lock:
            if now is not None:
                self._check_wedged_locked(now)
            return len(self._open)

    # -- read path ----------------------------------------------------------

    def _check_wedged_locked(self, now: float) -> None:
        """Grade the `wedged` rule lazily: the scheduler cannot
        observe its own stall, so the read path (and the per-finish
        `active_count`) checks whether requests are pending with no
        iteration observed for `stall_s`."""
        if not self._enabled["wedged"]:
            return
        th = self._th["wedged"]
        firing = (self._last_iter_ts is not None
                  and self._last_pending > 0
                  and now - self._last_iter_ts > th["stall_s"])
        dummy: list = []
        self._update_rule("wedged", firing, now,
                          {"stalled_s": (0.0 if self._last_iter_ts is None
                                         else now - self._last_iter_ts),
                           "pending": self._last_pending}, dummy)

    def active(self, now: float | None = None) -> tuple:
        """Names of the currently-open anomaly windows."""
        now = self._clock() if now is None else now
        with self._lock:
            self._check_wedged_locked(now)
            return tuple(sorted(self._open))

    def events(self, n: int | None = None) -> list[dict]:
        """The bounded anomaly-event ring, oldest first (`n` bounds
        from the newest end; n <= 0 means none, the /stats rule)."""
        if n is not None and n <= 0:
            return []
        with self._lock:
            evs = [dict(e, details=dict(e["details"]))
                   for e in self._events]
        return evs if n is None else evs[-n:]

    def stats(self, events: int = 8) -> dict:
        """The /stats `anomaly` block (scrape path)."""
        now = self._clock()
        with self._lock:
            self._check_wedged_locked(now)
            return {
                "active": sorted(self._open),
                "fired_total": dict(self.fired_total),
                "signals": {name: {"fast": pair[0], "slow": pair[1]}
                            for name, pair in self._ew.items()
                            if pair[0] is not None},
                "events": [dict(e, details=dict(e["details"]))
                           for e in list(self._events)[-events:]],
            }


def resolve_anomaly(anomaly, anomaly_config: str = ""
                    ) -> AnomalyWatchdog | None:
    """Same resolution contract as `resolve_fault_plan` (shared
    `_resolve_config` chain): a ready AnomalyWatchdog, a config dict
    / JSON string / file path, None (falling back to
    `InferConfig.anomaly_config`), or False. None means the watchdog
    is fully disabled (no rules, no events, byte-identical serving)."""
    return _resolve_config(anomaly, anomaly_config, AnomalyWatchdog,
                           "anomaly config")


def merge_anomaly_stats(stats_list) -> dict | None:
    """Fleet-wide anomaly view (`ReplicatedRouter.anomaly_stats`):
    `fired_total` counts sum per rule, `active` unions, per-replica
    events are tagged and interleaved by start time (counts sum,
    ratios would recompute — none exist here)."""
    stats_list = [s for s in stats_list if s]
    if not stats_list:
        return None
    out: dict = {"active": set(), "fired_total": {}, "events": []}
    for idx, st in enumerate(stats_list):
        out["active"].update(st.get("active", ()))
        for rule, n in st.get("fired_total", {}).items():
            out["fired_total"][rule] = out["fired_total"].get(rule, 0) + n
        for ev in st.get("events", ()):
            out["events"].append(dict(ev, replica=ev.get("replica", idx)))
    out["active"] = sorted(out["active"])
    out["events"].sort(key=lambda e: e["start"])
    return out

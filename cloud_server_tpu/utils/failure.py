"""Failure detection & elastic recovery.

Three cooperating pieces, all host-side (nothing here touches the jit
graph, so they cost nothing on-device):

* `NaNGuard` — a train-loop hook that checks the loss for NaN/inf on a
  cadence (cadenced because reading a device scalar synchronises the
  pipeline). On divergence it raises `TrainingDiverged`; the loop's
  exception path deliberately does NOT checkpoint, so the last *good*
  checkpoint survives and a relaunch resumes before the blow-up.

* `PreemptionHandler` — converts SIGTERM (the preemption notice every
  cloud scheduler sends) into a `KeyboardInterrupt` raised at the next
  step boundary. The loop catches it, force-saves the current state, and
  re-raises — turning an eviction into a clean elastic resume point.

* `Watchdog` — a heartbeat monitor thread for hang detection (a wedged
  collective, a stuck host callback, a dead data feed). If `beat()` is
  not called within `timeout_s`, it dumps every thread's stack to stderr
  and invokes `on_hang` (default: `os._exit(code)` so the scheduler
  restarts the job rather than letting it burn a TPU reservation forever).
  It only arms at the *first* beat, so an arbitrarily long first-step jit
  compile can't trigger it; `timeout_s` must still exceed the longest
  single beat-free operation (one step, one eval sweep, one checkpoint
  write — the train loop beats `beat()`-able hooks around each of these).

Elastic recovery itself is the composition: watchdog/preemption end the
process with state saved (or not, if diverged/hung), and
`training.loop.train_loop` + `checkpoint.restore_or_init` bring the next
process back on a possibly different topology (Orbax reshards on read).
"""

from __future__ import annotations

import faulthandler
import math
import os
import signal
import sys
import threading
import time
from typing import Callable

import jax


class TrainingDiverged(RuntimeError):
    """Loss became NaN/inf and stayed that way past the guard's patience."""


class NaNGuard:
    """Train-loop hook: raise `TrainingDiverged` on non-finite loss.

    check_interval: only inspect every k-th step (each inspection pulls a
    scalar from device, which blocks the async dispatch pipeline).
    patience: number of *consecutive checked* non-finite losses tolerated
    before raising — transient inf (e.g. one bad batch under bf16) can
    recover; a persistent NaN cannot.
    """

    def __init__(self, check_interval: int = 10, patience: int = 0,
                 metric: str = "loss"):
        self.check_interval = max(1, check_interval)
        self.patience = patience
        self.metric = metric
        self._bad_streak = 0

    def __call__(self, step: int, state, metrics: dict):
        if step % self.check_interval:
            return None
        value = float(jax.device_get(metrics[self.metric]))
        if math.isfinite(value):
            self._bad_streak = 0
            return None
        self._bad_streak += 1
        if self._bad_streak > self.patience:
            raise TrainingDiverged(
                f"{self.metric}={value} at step {step} "
                f"({self._bad_streak} consecutive bad checks)")
        return None


class PreemptionHandler:
    """Preemption signal -> KeyboardInterrupt at the next step boundary.

    Installs handlers for `signals` (default: SIGTERM only — SIGINT keeps
    Python's immediate Ctrl-C behaviour unless explicitly listed). Use as
    a context manager around the train loop; the inner hook only reads a
    flag, so the signal can arrive at any point (including inside XLA) and
    the interrupt still lands at a state-consistent boundary.
    """

    def __init__(self, signals=(signal.SIGTERM,)):
        self._signals = tuple(signals)
        self._previous: dict = {}
        self.requested = False

    def _handle(self, signum, frame):
        self.requested = True

    def __enter__(self) -> "PreemptionHandler":
        for s in self._signals:
            self._previous[s] = signal.signal(s, self._handle)
        return self

    def __exit__(self, *exc) -> None:
        for s, prev in self._previous.items():
            signal.signal(s, prev)
        self._previous.clear()

    def __call__(self, step: int, state, metrics: dict):
        """The train-loop hook."""
        if self.requested:
            raise KeyboardInterrupt(f"preemption requested (step {step})")
        return None


def _default_on_hang(timeout_s: float) -> None:
    print(f"[watchdog] no heartbeat for {timeout_s:.0f}s — dumping stacks "
          "and exiting", file=sys.stderr, flush=True)
    faulthandler.dump_traceback(file=sys.stderr)
    os._exit(42)


class CollectiveWatchdog:
    """Mesh-aware deadlock watchdog (see `Watchdog` for the mechanism).

    The classic distributed hang is a wedged collective: one participant
    on a mesh axis stops issuing its psum/ppermute/all_gather and every
    other device on that axis blocks forever. This wrapper (a) extends the
    timeout by `per_axis_s` for each comm-active mesh axis (axes of size
    > 1 — each adds a blocking dependency chain, e.g. pp stage handoffs on
    top of sp ring hops), and (b) names those axes in the hang report so
    the operator knows which collectives to suspect before reading stacks.

    Constructed like `Watchdog(...)` plus the mesh; use it anywhere a
    Watchdog is accepted (it is one, via delegation to an inner instance).
    """

    def __init__(self, mesh, timeout_s: float = 600.0,
                 per_axis_s: float = 60.0,
                 on_hang: Callable[[float], None] | None = None,
                 poll_s: float | None = None):
        axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        self.comm_axes = {a: s for a, s in axis_sizes.items() if s > 1}
        self._user_on_hang = on_hang or _default_on_hang
        self._inner = Watchdog(
            timeout_s=timeout_s + per_axis_s * len(self.comm_axes),
            on_hang=self._report, poll_s=poll_s)

    def _report(self, timeout_s: float) -> None:
        print(
            f"[watchdog] possible collective deadlock: no heartbeat for "
            f"{timeout_s:.0f}s with comm-active mesh axes "
            f"{self.comm_axes or '{} (single-device)'} — a wedged "
            "psum/ppermute/all_gather on any of these axes blocks every "
            "participant on it", file=sys.stderr, flush=True)
        self._user_on_hang(timeout_s)

    # Watchdog surface, delegated
    @property
    def timeout_s(self) -> float:
        return self._inner.timeout_s

    @property
    def fired(self) -> bool:
        return self._inner.fired

    def beat(self) -> None:
        self._inner.beat()

    def __call__(self, step: int, state, metrics: dict):
        return self._inner(step, state, metrics)

    def __enter__(self) -> "CollectiveWatchdog":
        self._inner.__enter__()
        return self

    def __exit__(self, *exc) -> None:
        self._inner.__exit__(*exc)


class Watchdog:
    """Heartbeat hang-detector.

    The protected code calls `beat()` periodically (e.g. via the train-loop
    hook interface: a Watchdog instance is itself a valid hook). A daemon
    thread checks the last heartbeat; silence past `timeout_s` triggers
    `on_hang(timeout_s)`. The monitor is disarmed until the first `beat()`
    (entering the context manager does not beat), so startup work of
    unknown length — first-step compilation in particular — can't fire it.
    """

    def __init__(self, timeout_s: float = 600.0,
                 on_hang: Callable[[float], None] | None = None,
                 poll_s: float | None = None):
        self.timeout_s = timeout_s
        self._on_hang = on_hang or _default_on_hang
        self._poll_s = poll_s if poll_s is not None else min(
            10.0, timeout_s / 4)
        self._stop = threading.Event()
        self._last_t: float | None = None
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self.fired = False

    def beat(self) -> None:
        with self._lock:
            self._last_t = time.monotonic()

    # hook interface
    def __call__(self, step: int, state, metrics: dict):
        self.beat()
        return None

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            with self._lock:
                last = self._last_t
            if last is not None and time.monotonic() - last > self.timeout_s:
                self.fired = True
                self._on_hang(self.timeout_s)
                return

    def __enter__(self) -> "Watchdog":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cloud-server-watchdog")
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class FaultInjector:
    """Deterministic fault injection for resilience drills and tests.

    A train-loop hook that fires configured faults at exact steps, so the
    recovery machinery (NaNGuard, PreemptionHandler, checkpoint resume,
    `_fail_all`-style unblocking) can be exercised on demand instead of
    waiting for real hardware flakiness. Faults:

      * "preempt"  — simulate a preemption signal at the step boundary
                     (raises KeyboardInterrupt, the same control flow a
                     SIGTERM produces through PreemptionHandler), which
                     the loop turns into an emergency checkpoint.
      * "nan_loss" — overwrite metrics[metric] with NaN so the NaNGuard
                     path (patience, divergence abort) is driven end to
                     end. Mutates the metrics dict only — model state is
                     untouched, mirroring a transient bad batch.
      * "crash"    — raise RuntimeError, the generic unrecoverable error.

    Faults are (step, kind) pairs; each fires once. The injector is a
    plain hook — compose it BEFORE the guards it is meant to trigger in
    the loop's hook list.
    """

    KINDS = ("preempt", "nan_loss", "crash")

    def __init__(self, faults: dict[int, str], metric: str = "loss"):
        for step, kind in faults.items():
            if kind not in self.KINDS:
                raise ValueError(f"unknown fault kind {kind!r} at step "
                                 f"{step}; expected one of {self.KINDS}")
        self._faults = dict(faults)
        self.metric = metric
        self.fired: list[tuple[int, str]] = []

    def __call__(self, step: int, state, metrics: dict):
        kind = self._faults.pop(step, None)
        if kind is None:
            return None
        self.fired.append((step, kind))
        if kind == "preempt":
            raise KeyboardInterrupt(f"injected preemption (step {step})")
        if kind == "nan_loss":
            import jax.numpy as jnp
            metrics[self.metric] = jnp.float32(float("nan"))
            return None
        raise RuntimeError(f"injected crash (step {step})")

"""Profiling hooks around jax.profiler.

`annotate` names a host-side region so it shows up on the TensorBoard
trace timeline; `capture_trace` wraps a step window in a full XLA/TPU
trace dump; `start_profiler_server` enables on-demand remote capture
(the standard workflow against a live training job).
"""

from __future__ import annotations

import contextlib
import os
from typing import Iterator

import jax


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def capture_trace(logdir: str | os.PathLike) -> Iterator[None]:
    """Capture a device+host trace for the enclosed block into `logdir`
    (view with TensorBoard's profile plugin or Perfetto)."""
    jax.profiler.start_trace(os.fspath(logdir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_profiler_server(port: int = 9999):
    """Expose this process to on-demand profiling (tensorboard capture)."""
    return jax.profiler.start_server(port)


class StepProfiler:
    """Trace a half-open step window [start, stop) of a training loop:
    profiles steady-state steps while skipping compile/warmup."""

    def __init__(self, logdir: str | os.PathLike, *, start_step: int,
                 num_steps: int = 3):
        self.logdir = os.fspath(logdir)
        self.start_step = start_step
        self.stop_step = start_step + num_steps
        self._active = False

    def step(self, step: int) -> None:
        if step == self.start_step and not self._active:
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif step >= self.stop_step and self._active:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False

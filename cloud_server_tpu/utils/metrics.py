"""Training metrics: FLOP accounting, step timing, windowed aggregation.

MFU follows the PaLM-style accounting: matmul FLOPs/token = 6·N (2·N
forward, 4·N backward) plus causal attention score/value FLOPs; the
denominator is the device's peak bf16 FLOPs (looked up from device_kind,
overridable). Numbers are comparable across frameworks because nothing
here depends on how the step is implemented.
"""

from __future__ import annotations

import collections
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from cloud_server_tpu.config import ModelConfig

# Peak dense bf16 FLOPs/s per chip. Extend as hardware appears.
DEVICE_PEAK_FLOPS: dict[str, float] = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "cpu": 1e11,  # nominal; keeps MFU finite in CPU tests
}


def peak_flops_per_device(default: float = 197e12) -> float:
    kind = jax.devices()[0].device_kind
    for name, peak in DEVICE_PEAK_FLOPS.items():
        if kind.lower().startswith(name.lower()):
            return peak
    return default


def param_count(params: Any) -> int:
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))


def transformer_flops_per_token(cfg: ModelConfig, seq_len: int,
                                n_params: int | None = None,
                                training: bool = True) -> float:
    """Matmul FLOPs per token for one step (fwd+bwd when training).

    6·N_matmul covers every weight matmul (embedding lookup is a gather,
    so the tied/untied lm_head is counted explicitly); attention adds
    2·2·S·H·Dh per token forward, halved for causality, tripled for bwd.
    """
    if n_params is None:
        D, L = cfg.embed_dim, cfg.num_layers
        per_layer = (D * cfg.num_heads * cfg.head_dim * 2  # wq, wo
                     + D * cfg.num_kv_heads * cfg.head_dim * 2  # wk, wv
                     + 3 * D * cfg.mlp_dim)  # gate, up, down
        n_params = L * per_layer + D * cfg.vocab_size  # + lm_head/tied
    mult = 3.0 if training else 1.0
    weight = 2.0 * mult * n_params
    attn = (2.0 * mult * 2 * seq_len * cfg.num_heads * cfg.head_dim
            * cfg.num_layers * 0.5)  # 0.5: causal
    return weight + attn


class StepTimer:
    """Wall-clock per-step timing -> tokens/sec and MFU.

    Call `tick(tokens_processed)` once per step *after* blocking on the
    step's output (jit steps return before the device finishes otherwise).
    Keeps a sliding window so throughput reflects steady state, not the
    compile step.
    """

    def __init__(self, *, flops_per_token: float | None = None,
                 n_devices: int | None = None,
                 peak_flops: float | None = None, window: int = 20):
        self.flops_per_token = flops_per_token
        self.n_devices = n_devices or jax.device_count()
        self.peak_flops = peak_flops or peak_flops_per_device()
        self._times: collections.deque = collections.deque(maxlen=window + 1)
        self._tokens: collections.deque = collections.deque(maxlen=window)
        self._times.append(time.perf_counter())

    def tick(self, tokens: int) -> dict[str, float]:
        self._times.append(time.perf_counter())
        self._tokens.append(tokens)
        dt = self._times[-1] - self._times[0]
        toks = sum(self._tokens)
        out = {"step_time_s": self._times[-1] - self._times[-2],
               "tokens_per_sec": toks / dt if dt > 0 else 0.0}
        if self.flops_per_token:
            out["mfu"] = (out["tokens_per_sec"] * self.flops_per_token
                          / (self.peak_flops * self.n_devices))
        return out


class MetricAggregator:
    """Mean-aggregates scalar metrics between log flushes (device scalars
    are only pulled to host at flush, keeping steps async)."""

    def __init__(self):
        self._sums: dict[str, float] = {}
        self._counts: dict[str, int] = {}
        self._pending: list[dict] = []

    def update(self, metrics: dict[str, Any]) -> None:
        self._pending.append(metrics)

    def flush(self) -> dict[str, float]:
        for metrics in self._pending:
            for k, v in metrics.items():
                v = float(jax.device_get(v)) if isinstance(
                    v, (jax.Array, jnp.ndarray)) else float(v)
                self._sums[k] = self._sums.get(k, 0.0) + v
                self._counts[k] = self._counts.get(k, 0) + 1
        self._pending.clear()
        out = {k: self._sums[k] / self._counts[k] for k in self._sums}
        self._sums.clear()
        self._counts.clear()
        return out

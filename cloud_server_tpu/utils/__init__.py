from cloud_server_tpu.utils.failure import (  # noqa: F401
    CollectiveWatchdog,
    NaNGuard,
    PreemptionHandler,
    TrainingDiverged,
    Watchdog,
)
from cloud_server_tpu.utils.logging import MetricLogger, read_jsonl  # noqa: F401
from cloud_server_tpu.utils.metrics import (  # noqa: F401
    DEVICE_PEAK_FLOPS,
    MetricAggregator,
    StepTimer,
    param_count,
    peak_flops_per_device,
    transformer_flops_per_token,
)
from cloud_server_tpu.utils.tracing import (  # noqa: F401
    StepProfiler,
    annotate,
    capture_trace,
    start_profiler_server,
)

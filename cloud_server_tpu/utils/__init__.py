from cloud_server_tpu.utils.failure import (  # noqa: F401
    CollectiveWatchdog,
    NaNGuard,
    PreemptionHandler,
    TrainingDiverged,
    Watchdog,
)
from cloud_server_tpu.utils.logging import (  # noqa: F401
    JsonLogger,
    MetricLogger,
    read_jsonl,
)
from cloud_server_tpu.utils.serving_metrics import (  # noqa: F401
    LATENCY_BUCKETS,
    FlightRecorder,
    MetricsRegistry,
    ServingMetrics,
    histogram_percentile,
    histogram_summary,
    merge_snapshots,
    render_prometheus,
)
from cloud_server_tpu.utils.metrics import (  # noqa: F401
    DEVICE_PEAK_FLOPS,
    MetricAggregator,
    StepTimer,
    param_count,
    peak_flops_per_device,
    transformer_flops_per_token,
)
from cloud_server_tpu.utils.tracing import (  # noqa: F401
    StepProfiler,
    annotate,
    capture_trace,
    start_profiler_server,
)

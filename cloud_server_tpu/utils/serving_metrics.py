"""Serving metrics: counters, gauges, fixed-bucket histograms, and the
scheduler flight recorder.

The serving hot path must never pay a device sync or a dispatch for
telemetry, so everything here is plain host-side arithmetic on floats
the scheduler already has in hand (`time.perf_counter()` taken at
points where the host blocks anyway — see the servers' lifecycle
notes). `observe()` is a bisect + two adds under a small lock; a
snapshot is a deep copy taken on the scrape path, never the serving
path.

Naming: every metric carries the `cloud_server_` namespace so a
Prometheus scrape of a mixed fleet is unambiguous. The full catalog
lives in docs/observability.md and is drift-checked by
tests/test_observability.py — register a metric and the test fails
until the catalog documents it.

Snapshots are plain dicts (`{name: {"type", "help", ...}}`) so they
merge across replicas (`merge_snapshots`, used by ReplicatedRouter to
report fleet-wide percentiles: histogram buckets add, counters add,
gauges add — occupancy gauges are totals, so summation is the right
fleet semantics) and render to the Prometheus text exposition
(`render_prometheus`) without the registry objects ever crossing a
process or thread boundary.
"""

from __future__ import annotations

import bisect
import collections
import threading
import time
from typing import Callable, Iterable, Sequence

NAMESPACE = "cloud_server"

# Per-tenant TTFT histogram family (multi-tenant QoS): one labeled
# series per tenant, observed once per request at first token. Shared
# between ServingMetrics.observe_emit (the observation) and
# TenantRegistry.mirror_metrics (eager registration, so the family
# exists — and the docs drift check sees it — before any traffic).
TENANT_TTFT = ("tenant_ttft_seconds",
               "Time from submit to first emitted token, per tenant")

# Shared latency bucket ladder (seconds): sub-ms through minutes, the
# span TTFT/ITL/queue-wait cover between a warm single-chip deployment
# and a cold multi-minute drain. Fixed at registration so merge() across
# replicas is exact (identical edges everywhere).
LATENCY_BUCKETS: tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)


def _full_name(name: str) -> str:
    return name if name.startswith(f"{NAMESPACE}_") else \
        f"{NAMESPACE}_{name}"


def _escape_label_value(v: str) -> str:
    """Prometheus text-format label escaping — label values may come
    from untrusted client headers (tenant names)."""
    return (str(v).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _label_suffix(labels: dict[str, str] | None) -> str:
    """Prometheus label block for a series key ('' when unlabeled).
    Sorted so the same label set always yields the same series key —
    which is what lets `merge_snapshots` add labeled series across
    replicas by plain string key."""
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    """Monotonic counter. `inc` is the hot-path op; `set_total` exists
    for mirroring an externally-maintained monotonic count (e.g. the
    allocator's lifetime eviction count) into a snapshot collector."""

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self.labels: dict[str, str] | None = None
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def set_total(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        out = {"type": "counter", "help": self.help, "value": self._value}
        if self.labels:
            out["labels"] = dict(self.labels)
        return out


class Gauge:
    """Point-in-time value (occupancy, queue depth, pool free pages)."""

    def __init__(self, name: str, help_text: str):
        self.name = name
        self.help = help_text
        self.labels: dict[str, str] | None = None
        self._value = 0.0

    def set(self, value: float) -> None:
        self._value = float(value)

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> dict:
        out = {"type": "gauge", "help": self.help, "value": self._value}
        if self.labels:
            out["labels"] = dict(self.labels)
        return out


class Histogram:
    """Fixed-bucket histogram with cheap host-side observe().

    Buckets are UPPER BOUNDS (Prometheus `le` semantics, cumulative at
    render time); counts are kept per-bucket (non-cumulative) plus an
    overflow bucket, so observe() is one bisect and two adds. Edges are
    fixed at construction so snapshots from different replicas merge
    bucket-for-bucket."""

    def __init__(self, name: str, help_text: str,
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError("histogram buckets must be a sorted, "
                             "non-empty sequence of upper bounds")
        self.name = name
        self.help = help_text
        self.labels: dict[str, str] | None = None
        self.buckets = tuple(float(b) for b in buckets)
        self._counts = [0] * (len(self.buckets) + 1)  # +1: +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def snapshot(self) -> dict:
        with self._lock:
            out = {"type": "histogram", "help": self.help,
                   "buckets": list(self.buckets),
                   "counts": list(self._counts),
                   "sum": self._sum, "count": self._count}
        if self.labels:
            out["labels"] = dict(self.labels)
        return out


class MetricsRegistry:
    """Get-or-create registry; the single source of truth for which
    metric names exist at runtime (the docs drift check enumerates a
    snapshot's keys). Collectors are callbacks run at snapshot time so
    externally-owned state (scheduler occupancy, allocator stats) is
    mirrored on the SCRAPE path, not the serving path."""

    def __init__(self):
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._collectors: list[Callable[[], None]] = []
        self._lock = threading.Lock()

    def _get_or_create(self, cls, name: str, help_text: str,
                       labels: dict[str, str] | None, *args):
        name = _full_name(name)
        key = name + _label_suffix(labels)
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help_text, *args)
                if labels:
                    m.labels = {str(k): str(v)
                                for k, v in labels.items()}
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {key} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str, help_text: str = "",
                labels: dict[str, str] | None = None) -> Counter:
        return self._get_or_create(Counter, name, help_text, labels)

    def gauge(self, name: str, help_text: str = "",
              labels: dict[str, str] | None = None) -> Gauge:
        return self._get_or_create(Gauge, name, help_text, labels)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Sequence[float] = LATENCY_BUCKETS,
                  labels: dict[str, str] | None = None) -> Histogram:
        return self._get_or_create(Histogram, name, help_text, labels,
                                   buckets)

    def add_collector(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._collectors.append(fn)

    def snapshot(self) -> dict[str, dict]:
        for fn in list(self._collectors):
            fn()
        with self._lock:
            return {name: m.snapshot()
                    for name, m in sorted(self._metrics.items())}


def merge_snapshots(snaps: Iterable[dict[str, dict]]) -> dict[str, dict]:
    """Merge registry snapshots (e.g. one per replica) into a
    fleet-wide snapshot: counters and gauges add; histograms add
    bucket-for-bucket (edges must match — they do, by construction:
    every replica registers the same fixed ladders)."""
    out: dict[str, dict] = {}
    for snap in snaps:
        for name, entry in snap.items():
            cur = out.get(name)
            if cur is None:
                out[name] = {k: (list(v) if isinstance(v, list) else
                                 dict(v) if isinstance(v, dict) else v)
                             for k, v in entry.items()}
                continue
            if cur["type"] != entry["type"]:
                raise ValueError(f"metric {name} has conflicting types "
                                 f"across snapshots: {cur['type']} vs "
                                 f"{entry['type']}")
            if entry["type"] == "histogram":
                if cur["buckets"] != entry["buckets"]:
                    raise ValueError(
                        f"histogram {name} has mismatched bucket edges "
                        "across snapshots; merge needs identical ladders")
                cur["counts"] = [a + b for a, b in
                                 zip(cur["counts"], entry["counts"])]
                cur["sum"] += entry["sum"]
                cur["count"] += entry["count"]
            else:
                cur["value"] += entry["value"]
    return dict(sorted(out.items()))


def histogram_percentile(entry: dict, q: float) -> float:
    """Estimate the q-quantile (0..1) of a histogram snapshot entry by
    linear interpolation inside the containing bucket (the Prometheus
    `histogram_quantile` rule). The overflow bucket clamps to the top
    edge. Returns 0.0 for an empty histogram."""
    total = entry["count"]
    if total <= 0:
        return 0.0
    target = q * total
    edges = entry["buckets"]
    seen = 0.0
    for i, c in enumerate(entry["counts"]):
        if seen + c >= target and c > 0:
            lo = 0.0 if i == 0 else edges[i - 1]
            hi = edges[i] if i < len(edges) else edges[-1]
            frac = (target - seen) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        seen += c
    return edges[-1]


def histogram_summary(entry: dict) -> dict:
    """Compact JSON summary for the /stats endpoint: count, mean, and
    interpolated p50/p95/p99."""
    count = entry["count"]
    return {"count": count, "sum": entry["sum"],
            "mean": entry["sum"] / count if count else 0.0,
            "p50": histogram_percentile(entry, 0.50),
            "p95": histogram_percentile(entry, 0.95),
            "p99": histogram_percentile(entry, 0.99)}


def render_prometheus(snapshot: dict[str, dict]) -> str:
    """Prometheus text exposition (version 0.0.4) of a snapshot: every
    metric FAMILY gets exactly one HELP and one TYPE line (labeled
    series — snapshot keys like `name{tenant="a"}` — share their
    family's metadata); histograms render cumulative `_bucket{le=...}`
    series plus `_sum`/`_count`, with the series' own labels folded in
    ahead of `le`."""
    out: list[str] = []
    seen_meta: set[str] = set()
    # group by FAMILY, not raw key: the exposition format wants every
    # series of a family contiguous under one HELP/TYPE, and a plain
    # key sort can interleave (`foo_bar` sorts between `foo` and
    # `foo{...}` because "_" < "{"). Sorting here also makes the output
    # independent of snapshot dict ordering.
    for name, entry in sorted(
            snapshot.items(),
            key=lambda kv: (kv[0].partition("{")[0], kv[0])):
        base, _, label_rest = name.partition("{")
        labels = "{" + label_rest if label_rest else ""
        # labels without the closing brace, for composing with `le`
        inner = label_rest[:-1] + "," if label_rest else ""
        if base not in seen_meta:
            out.append(f"# HELP {base} {entry.get('help', '')}")
            out.append(f"# TYPE {base} {entry['type']}")
            seen_meta.add(base)
        if entry["type"] == "histogram":
            cum = 0
            for edge, c in zip(entry["buckets"], entry["counts"]):
                cum += c
                out.append(
                    f'{base}_bucket{{{inner}le="{edge:g}"}} {cum}')
            cum += entry["counts"][-1]
            out.append(f'{base}_bucket{{{inner}le="+Inf"}} {cum}')
            out.append(f"{base}_sum{labels} {entry['sum']}")
            out.append(f"{base}_count{labels} {entry['count']}")
        else:
            out.append(f"{base}{labels} {entry['value']}")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# Request-lifecycle instruments shared by both servers
# ---------------------------------------------------------------------------


class ServingMetrics:
    """The standard serving instrument set, registered once per server.

    All observe_* hooks take timestamps the scheduler already recorded
    on the request (host wall clock at points where the host blocks on
    device output anyway), so instrumentation adds zero device syncs
    and zero dispatches — guarded by the dispatch-count regression test
    in tests/test_observability.py.

    `slo` (an inference.slo.SLOTracker, attached by a server that
    resolved an SLO config) receives the same latency observations,
    tagged with the request's priority class (`req.slo_class`), at the
    same already-owned host moments; None (the default) keeps every
    hook byte-identical to the pre-SLO build."""

    def __init__(self, registry: MetricsRegistry | None = None,
                 slo=None):
        self.slo = slo
        r = self.registry = registry or MetricsRegistry()
        self.ttft = r.histogram(
            "ttft_seconds", "Time from submit to first emitted token")
        self.itl = r.histogram(
            "itl_seconds", "Inter-token latency between emitted tokens")
        self.queue_wait = r.histogram(
            "queue_wait_seconds",
            "Time from submit to first admission into a slot")
        self.e2e = r.histogram(
            "e2e_seconds", "Time from submit to request completion")
        self.submitted = r.counter(
            "requests_submitted_total", "Requests accepted by submit()")
        self.finished = r.counter(
            "requests_finished_total",
            "Requests completed normally (eos / stop / length)")
        self.cancelled = r.counter(
            "requests_cancelled_total", "Requests cancelled by the client")
        self.failed = r.counter(
            "requests_failed_total",
            "Requests failed by a scheduler/server error")
        self.requeues = r.counter(
            "preempt_requeues_total",
            "Requests requeued after an on-demand-paging preemption")
        self.deadline_expired = r.counter(
            "deadline_expired_total",
            "Requests cancelled by the scheduler sweep because their "
            "deadline passed")

    def observe_submit(self, req) -> None:
        self.submitted.inc()

    def observe_admit(self, req, now: float) -> None:
        req.record_event("admit", now)
        if req.admit_time is None:
            req.admit_time = now
            if req.submit_time is not None:
                self.queue_wait.observe(now - req.submit_time)
                if self.slo is not None:
                    self.slo.observe(req.slo_class, "queue_wait",
                                     now - req.submit_time, now)

    def observe_emit(self, req) -> None:
        """Called after emit_token appended a timestamp (the host moment
        the token surfaced — already taken; nothing re-reads the clock
        here)."""
        times = req.emit_times
        if len(times) == 1:
            req.record_event("first_token", times[0])
            if req.submit_time is not None:
                ttft = times[0] - req.submit_time
                self.ttft.observe(ttft)
                if self.slo is not None:
                    self.slo.observe(req.slo_class, "ttft", ttft,
                                     times[0])
                tenant = getattr(req, "tenant", None)
                if tenant:
                    # once per request (not per token): the per-tenant
                    # latency view QoS isolation is judged by
                    self.registry.histogram(
                        *TENANT_TTFT,
                        labels={"tenant": tenant}).observe(ttft)
        elif len(times) >= 2:
            self.itl.observe(times[-1] - times[-2])
            if self.slo is not None:
                self.slo.observe(req.slo_class, "itl",
                                 times[-1] - times[-2], times[-1])

    def observe_requeue(self, req, now: float) -> None:
        req.record_event("preempt_requeue", now)
        self.requeues.inc()

    def observe_finish(self, req, now: float | None = None) -> float:
        """Terminal-state bookkeeping; returns the finish moment so
        callers (tail retention, the anomaly watchdog) reuse the one
        timestamp instead of re-reading the clock."""
        now = time.perf_counter() if now is None else now
        reason = req.finish_reason or ""
        req.record_event(f"finish:{reason}", now)
        if reason == "cancelled":
            self.cancelled.inc()
        elif reason == "deadline":
            self.deadline_expired.inc()
        elif reason.startswith("error"):
            self.failed.inc()
        else:
            self.finished.inc()
        if req.submit_time is not None:
            self.e2e.observe(now - req.submit_time)
            if self.slo is not None:
                self.slo.observe(req.slo_class, "e2e",
                                 now - req.submit_time, now)
        return now


# ---------------------------------------------------------------------------
# Scheduler flight recorder
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Fixed-size ring buffer of per-iteration scheduler records for
    post-mortem debugging (the PR 2 churn cliff was exactly the kind of
    behavior only visible iteration-by-iteration: decode round counts
    collapsing while admission jobs were in flight).

    A record is a plain dict; the scheduler writes whatever fields the
    iteration produced (token-budget utilization, prefill/decode token
    split, live-slot occupancy, compaction ratio, preemption/requeue
    counts). `record()` is an O(1) deque append on the scheduler
    thread; `window()` copies on the scrape path only."""

    def __init__(self, capacity: int = 256):
        if capacity <= 0:
            raise ValueError("flight recorder capacity must be positive")
        self.capacity = capacity
        self._buf: collections.deque[dict] = collections.deque(
            maxlen=capacity)
        self._seq = 0

    def record(self, **fields) -> None:
        self._seq += 1
        fields["iteration"] = self._seq
        self._buf.append(fields)

    def __len__(self) -> int:
        return len(self._buf)

    @property
    def iterations(self) -> int:
        return self._seq

    def window(self, n: int | None = None) -> list[dict]:
        buf = list(self._buf)
        return buf if n is None else buf[-n:]

"""Shared workload helpers for bench.py's serving sections.

Every churn/QoS/fault/disagg section used to carry its own copy of
the same three closures — a seeded random-prompt maker, a sorted-list
percentile, and the keep-the-scheduler-fed top-up. Factored here so
the sections (and the `slo_autoscale` section) agree on one
definition; the sampling idiom (numpy RandomState, vocab [1, 30000))
is unchanged, so existing sections measure the same token streams
they always did.

This module is bench-side tooling, not serving code: numpy is fine
here (it is NOT on the analysis DD3/host-policy rosters, and nothing
in the serving path imports it).
"""

from __future__ import annotations


def make_prompt_fn(seed: int = 0, vocab: int = 30000):
    """A seeded `mk_prompt(n) -> list[int]` closure — each bench
    section gets its own stream (sections historically seed 0)."""
    import numpy as np
    rng = np.random.RandomState(seed)

    def mk_prompt(n: int) -> list[int]:
        return [int(x) for x in rng.randint(1, vocab, size=n)]

    return mk_prompt


def pct(xs, p: float) -> float:
    """Sorted-list percentile, the bench sections' shared definition
    (index floor, no interpolation); 0.0 on empty."""
    xs = sorted(xs)
    if not xs:
        return 0.0
    return xs[min(len(xs) - 1, int(p * len(xs)))]


def top_up(srv, mk_prompt, *, prompt_len: int = 64,
           max_new_tokens: int = 256) -> None:
    """Keep a scheduler fed: iteration-driven telemetry (the anomaly
    watchdog, flight records) only observes BUSY iterations, so
    measured windows need the queue to never run dry."""
    if not (srv._jobs or srv.num_pending or srv.num_active):
        srv.submit(mk_prompt(prompt_len), max_new_tokens=max_new_tokens)

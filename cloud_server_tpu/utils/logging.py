"""Structured metric logging: JSONL on disk + human lines on stdout.

JSONL because every downstream consumer (plotting, regression gates, the
bench driver) wants machine-readable step records; stdout stays terse.
Process-0-only by default so multi-host runs don't write N copies.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, TextIO

import jax


class MetricLogger:
    def __init__(self, logdir: str | os.PathLike | None = None, *,
                 name: str = "train", stream: TextIO | None = None,
                 only_process_zero: bool = True):
        self._enabled = (not only_process_zero) or jax.process_index() == 0
        self._stream = stream if stream is not None else sys.stdout
        self._file = None
        if self._enabled and logdir is not None:
            os.makedirs(logdir, exist_ok=True)
            self._file = open(os.path.join(os.fspath(logdir),
                                           f"{name}.jsonl"), "a")

    def log(self, step: int, metrics: dict[str, Any]) -> None:
        if not self._enabled:
            return
        record = {"step": int(step), "time": time.time()}
        record.update({k: float(v) for k, v in metrics.items()})
        if self._file is not None:
            self._file.write(json.dumps(record) + "\n")
            self._file.flush()
        body = " ".join(f"{k}={v:.4g}" for k, v in record.items()
                        if k not in ("step", "time"))
        print(f"[step {step}] {body}", file=self._stream)

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None

    def __enter__(self) -> "MetricLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_jsonl(path: str | os.PathLike) -> list[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


class JsonLogger:
    """Structured JSONL event log: one JSON object per line, to a file
    and/or a stream (default stderr — access logs must not interleave
    with stdout protocol output like bench JSON lines).

    The serving front-end builds its opt-in HTTP access log on this
    (method, path, status, duration, request id); anything that wants a
    machine-readable event trail can reuse it. Writes are serialised by
    a lock so concurrent handler threads never interleave lines."""

    def __init__(self, path: str | os.PathLike | None = None, *,
                 stream: TextIO | None = None):
        import threading
        self._lock = threading.Lock()
        self._file = open(os.fspath(path), "a") if path is not None else None
        # explicit stream wins; file-only when a path was given; else
        # stderr so an argument-free JsonLogger() is still observable
        self._stream = stream if stream is not None else (
            None if self._file is not None else sys.stderr)

    def log(self, record: dict) -> None:
        line = json.dumps({"time": time.time(), **record})
        with self._lock:
            if self._file is not None:
                self._file.write(line + "\n")
                self._file.flush()
            if self._stream is not None:
                print(line, file=self._stream)

    def close(self) -> None:
        # under the lock: an unjoined handler thread (daemon HTTP
        # handlers outlive stop()) may be mid-log()
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    def __enter__(self) -> "JsonLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

"""Lock-discipline pass (checker id: ``lock-discipline``).

The serving stack shares state between client threads (submit /
cancel / scrape) and the scheduler thread through two mutexes:
``self._lock`` guards the pending queue, the draining latch, and the
small registries, while ``self._step_lock`` serializes the whole
scheduler iteration. This pass infers that discipline per class and
flags code that steps outside it.

Model (per class that assigns ``self.<name> = threading.Lock()``):

  1. Lexical lock regions: statements inside ``with self.<lock>:``,
     plus the bounded-acquire teardown idiom — after
     ``got = self.<lock>.acquire(timeout=...)`` the remainder of the
     enclosing block counts as holding the lock (for paths that must
     not hang behind a wedged holder; release is assumed at block
     end via try/finally).
  2. A class-local call graph (``self.m(...)`` calls, plus reads of
     ``@property`` attributes) propagates held locks:
     ``must_held(m)`` = locks held at EVERY internal call site
     (public methods are entry points: must_held is empty — an
     external caller holds nothing); ``may_held(m)`` = locks held at
     ANY internal call site.
  3. GUARDED-ATTRIBUTE inference: every ``self._*`` attribute written
     (assign / augassign / del / subscript-store / mutating method
     call: append, remove, update, ...) while at least one lock is
     must-held, anywhere in the class, is shared state. Its guard is
     the INTERSECTION of the lock sets across those writes — the
     locks every writer agrees on. ``__init__`` is construction-time
     and excluded entirely.

Rules:

  * ``LD1 unlocked access`` — a read or write of a guarded attribute
    at a point whose must-held set shares no lock with the guard.
  * ``LD2 split guard`` — an attribute whose locked writes share NO
    common lock (two writers that can race each other).
  * ``LD3 blocking under lock`` — a blocking call while any lock may
    be held: ``device_get`` / ``block_until_ready``, ``time.sleep``
    (any ``.sleep``), host I/O (``print`` / ``open`` / ``input``),
    socket ops (``recv`` / ``send`` / ``sendall`` / ``accept`` /
    ``connect``), and ``<queue>.get()`` with no timeout.
  * ``LD4 lock order`` — ``LOCK_ORDER`` declares ``_step_lock`` is
    taken BEFORE ``_lock`` (the order ``PagedInferenceServer.step``
    -> ``_record_iteration`` -> ``num_pending`` established);
    acquiring against that order, or acquiring a lock that may
    already be held (self-deadlock — these are not RLocks), flags.

Known limits (deliberate, documented): the analysis is class-local
(a qos registry's lock taken under the server's step lock is a
different object — cross-object ordering is out of scope); nested
functions are scanned at their definition site's lock state; and
must-held is conservative, so a teardown-only caller (e.g. a
post-mortem ``_fail_all``) weakens the guard inference of everything
it calls — which is exactly why ``_fail_all`` serializes on the step
lock too.
"""

from __future__ import annotations

import ast

from cloud_server_tpu.analysis.framework import (Finding, Pass,
                                                 default_root,
                                                 dotted_name,
                                                 read_rostered,
                                                 register_pass)

CHECKER = "lock-discipline"

# The serving modules whose cross-thread state this pass audits (the
# two servers' shared-state mutexes plus every policy/telemetry module
# the scheduler iteration consults).
LOCK_ROSTER: tuple[str, ...] = (
    "cloud_server_tpu/inference/paged_server.py",
    "cloud_server_tpu/inference/qos.py",
    "cloud_server_tpu/inference/faults.py",
    "cloud_server_tpu/inference/migration.py",
    "cloud_server_tpu/inference/router.py",
    "cloud_server_tpu/inference/request_trace.py",
    "cloud_server_tpu/inference/slo.py",
    "cloud_server_tpu/inference/cache_telemetry.py",
    "cloud_server_tpu/inference/anomaly.py",
)

# Declared acquisition order, outermost first: the scheduler iteration
# (_step_lock) may take the state mutex (_lock) inside it, never the
# reverse — a client thread holding _lock while waiting on a running
# iteration would stall submit/cancel behind a whole dispatch.
LOCK_ORDER: tuple[str, ...] = ("_step_lock", "_lock")

_LOCK_CTORS = {"Lock", "RLock"}
# attribute method calls treated as WRITES to the attribute
_MUTATORS = {"append", "appendleft", "extend", "insert", "remove",
             "pop", "popleft", "clear", "update", "setdefault",
             "discard", "add"}
# call leaves that block the holding thread
_BLOCKING_LEAVES = {"device_get", "block_until_ready", "sleep"}
_BLOCKING_NAMES = {"print", "open", "input"}
_SOCKET_LEAVES = {"recv", "recvfrom", "send", "sendall", "accept",
                  "connect"}
_SKIP_METHODS = {"__init__", "__post_init__", "__new__"}


_dotted = dotted_name


def _self_attr(node: ast.AST) -> str | None:
    """'x' for a `self.x` attribute node, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = _dotted(node.func)
    return name is not None and name.split(".")[-1] in _LOCK_CTORS


class _Access:
    __slots__ = ("attr", "write", "node", "held")

    def __init__(self, attr, write, node, held):
        self.attr, self.write = attr, write
        self.node, self.held = node, held


class _MethodScan:
    """Lexical facts about one method: self-attribute accesses, lock
    acquisitions, internal call sites, and blocking calls — each with
    the set of locks lexically held at that point."""

    def __init__(self):
        self.accesses: list[_Access] = []
        self.acquires: list[tuple[str, ast.AST, frozenset]] = []
        self.calls: list[tuple[str, frozenset]] = []
        self.blocking: list[tuple[str, ast.AST, frozenset]] = []


class _ClassAnalysis:
    def __init__(self, path: str, node: ast.ClassDef):
        self.path = path
        self.node = node
        self.methods: dict[str, ast.AST] = {}
        self.properties: set[str] = set()
        for child in node.body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[child.name] = child
                for dec in child.decorator_list:
                    if (isinstance(dec, ast.Name)
                            and dec.id == "property"):
                        self.properties.add(child.name)
        # lock attributes: assigned a Lock()/RLock() anywhere
        self.locks: set[str] = set()
        for fn in self.methods.values():
            for n in ast.walk(fn):
                if isinstance(n, ast.Assign) and _is_lock_ctor(n.value):
                    for tgt in n.targets:
                        attr = _self_attr(tgt)
                        if attr is not None:
                            self.locks.add(attr)
        # condition variables constructed OVER a class lock alias it:
        # `self._work = threading.Condition(self._lock)` means `with
        # self._work:` holds _lock (that IS the Condition's mutex), so
        # guarded-attribute checks must credit it
        self.lock_aliases: dict[str, str] = {}
        for fn in self.methods.values():
            for n in ast.walk(fn):
                if (isinstance(n, ast.Assign)
                        and isinstance(n.value, ast.Call)):
                    name = _dotted(n.value.func)
                    if (name is not None
                            and name.split(".")[-1] == "Condition"
                            and n.value.args):
                        src = _self_attr(n.value.args[0])
                        if src in self.locks:
                            for tgt in n.targets:
                                attr = _self_attr(tgt)
                                if attr is not None:
                                    self.lock_aliases[attr] = src
        self.scans: dict[str, _MethodScan] = {}

    # -- lexical scan -------------------------------------------------------

    def scan(self) -> None:
        for name, fn in self.methods.items():
            if name in _SKIP_METHODS:
                continue
            ms = _MethodScan()
            self._visit_body(fn.body, frozenset(), ms)
            self.scans[name] = ms

    def _bounded_acquire(self, stmt: ast.AST) -> str | None:
        """Lock name for the bounded-acquire teardown idiom
        ``got = self.<lock>.acquire(timeout=...)`` — a path that must
        not hang takes the lock with a timeout and proceeds either
        way; the rest of the block is treated as holding it."""
        if not (isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr == "acquire"):
            return None
        attr = _self_attr(stmt.value.func.value)
        return attr if attr in self.locks else None

    def _visit_body(self, stmts, held: frozenset,
                    ms: _MethodScan) -> None:
        for stmt in stmts:
            self._visit(stmt, held, ms)
            lock = self._bounded_acquire(stmt)
            if lock is not None:
                ms.acquires.append((lock, stmt, held))
                held = held | {lock}

    def _visit(self, node: ast.AST, held: frozenset,
               ms: _MethodScan) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = set()
            for item in node.items:
                attr = _self_attr(item.context_expr)
                attr = self.lock_aliases.get(attr, attr)
                if attr in self.locks:
                    # items acquire LEFT TO RIGHT: each sees the locks
                    # the earlier items already took, so a one-liner
                    # `with self._lock, self._step_lock:` trips the
                    # same LD4 rules as the nested form
                    ms.acquires.append((attr, item.context_expr,
                                        held | acquired))
                    acquired.add(attr)
                else:
                    self._visit(item.context_expr, held | acquired, ms)
                if item.optional_vars is not None:
                    self._visit(item.optional_vars, held | acquired, ms)
            self._visit_body(node.body, held | acquired, ms)
            return
        if isinstance(node, ast.Call):
            self._visit_call(node, held, ms)
            return
        if isinstance(node, ast.Subscript):
            # `self._x[k] = v` / `del self._x[k]`: the Store/Del ctx
            # sits on the Subscript — the inner Attribute reads as
            # Load — but semantically this WRITES the container
            attr = _self_attr(node.value)
            if (attr is not None
                    and isinstance(node.ctx, (ast.Store, ast.Del))):
                self._record_attr(node.value, attr, held, ms,
                                  write=True)
                self._visit(node.slice, held, ms)
                return
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is not None:
                self._record_attr(node, attr, held, ms)
                return  # the Name('self') child is not an access
        for field, value in ast.iter_fields(node):
            if isinstance(value, list) and value \
                    and isinstance(value[0], ast.stmt):
                self._visit_body(value, held, ms)
            elif isinstance(value, list):
                for child in value:
                    if isinstance(child, ast.AST):
                        self._visit(child, held, ms)
            elif isinstance(value, ast.AST):
                self._visit(value, held, ms)

    def _record_attr(self, node: ast.Attribute, attr: str,
                     held: frozenset, ms: _MethodScan,
                     write: bool | None = None) -> None:
        if attr in self.locks or attr in self.lock_aliases:
            return
        if attr in self.properties:
            # a property read runs the getter: a call-graph edge
            ms.calls.append((attr, held))
            return
        if attr in self.methods:
            return  # bare method reference (callback assignment)
        if write is None:
            write = isinstance(node.ctx, (ast.Store, ast.Del))
        ms.accesses.append(_Access(attr, write, node, held))

    def _visit_call(self, node: ast.Call, held: frozenset,
                    ms: _MethodScan) -> None:
        func = node.func
        handled_func = False
        recv_attr = None
        if isinstance(func, ast.Attribute):
            leaf = func.attr
            recv_attr = _self_attr(func.value)
            if recv_attr is not None and leaf in _MUTATORS \
                    and recv_attr not in self.locks \
                    and recv_attr not in self.methods:
                # self._x.append(...) — a write to _x
                ms.accesses.append(_Access(recv_attr, True, func.value,
                                           held))
                handled_func = True
            name = _dotted(func) or leaf
            if leaf in _BLOCKING_LEAVES or leaf in _SOCKET_LEAVES:
                ms.blocking.append((f"blocking call {name}()", node,
                                    held))
            elif leaf == "get" and not node.args:
                recv = _dotted(func.value) or ""
                if ("queue" in recv.lower()
                        and not any(kw.arg == "timeout"
                                    for kw in node.keywords)):
                    ms.blocking.append(
                        (f"unbounded {name}() — a queue get with no "
                         "timeout", node, held))
            mname = _self_attr(func)
            if mname is not None and mname in self.methods:
                ms.calls.append((mname, held))
                handled_func = True
        elif isinstance(func, ast.Name):
            if func.id in _BLOCKING_NAMES:
                ms.blocking.append((f"host I/O call {func.id}()", node,
                                    held))
            handled_func = True  # a bare name is not a self access
        if not handled_func:
            self._visit(func, held, ms)
        for arg in node.args:
            self._visit(arg, held, ms)
        for kw in node.keywords:
            self._visit(kw.value, held, ms)

    # -- inter-procedural held-lock propagation -----------------------------

    def propagate(self) -> tuple[dict[str, frozenset],
                                 dict[str, frozenset]]:
        """(must_held, may_held) per method, to fixpoint over the
        class-local call graph. Public methods (and methods never
        called internally) are entry points: must_held = {} — some
        caller out there holds nothing."""
        sites: dict[str, list[tuple[str, frozenset]]] = {
            m: [] for m in self.scans}
        for caller, ms in self.scans.items():
            for callee, held in ms.calls:
                if callee in sites:
                    sites[callee].append((caller, held))
        all_locks = frozenset(self.locks)
        must = {}
        may = {m: frozenset() for m in self.scans}
        for m in self.scans:
            entry = not m.startswith("_") or m.startswith("__") \
                or not sites[m]
            must[m] = frozenset() if entry else all_locks
        changed = True
        while changed:
            changed = False
            for m in self.scans:
                if not sites[m]:
                    continue
                new_may = frozenset().union(
                    *[held | may[c] for c, held in sites[m]])
                if new_may != may[m]:
                    may[m] = new_may
                    changed = True
                if must[m]:  # entry points stay pinned at {}
                    new_must = all_locks
                    for c, held in sites[m]:
                        new_must &= held | must[c]
                    if new_must != must[m]:
                        must[m] = new_must
                        changed = True
        return must, may

    # -- rules --------------------------------------------------------------

    def infer_guards(self, must: dict[str, frozenset]
                     ) -> tuple[dict[str, frozenset], list[Finding]]:
        """Guarded-attribute inference from locked writes: every
        ``self._*`` attribute written while a lock is must-held is
        shared state, guarded by the INTERSECTION of the lock sets
        across its locked writes. Returns ({attr: guard}, LD2
        split-guard findings). Shared with the lifecycle pass, whose
        LC4 torn-write rule consumes the same guard sets."""
        out: list[Finding] = []
        cls = self.node.name
        locked_writes: dict[str, list[frozenset]] = {}
        for m, ms in self.scans.items():
            for a in ms.accesses:
                if a.write and a.attr.startswith("_"):
                    locks_at = a.held | must[m]
                    if locks_at:
                        locked_writes.setdefault(a.attr, []).append(
                            locks_at)
        guard: dict[str, frozenset] = {}
        for attr, sets in locked_writes.items():
            g = frozenset.intersection(*sets)
            if g:
                guard[attr] = g
            else:
                some = sorted(frozenset.union(*sets))
                out.append(Finding(
                    self.path, self.node.lineno, CHECKER,
                    f"{cls}.{attr}",
                    f"split guard: {attr} is written under "
                    f"{some} with no common lock — two writers can "
                    "race (LD2)"))
        return guard, out

    def check(self) -> list[Finding]:
        if not self.locks:
            return []
        self.scan()
        must, may = self.propagate()
        cls = self.node.name
        guard, out = self.infer_guards(must)

        rank = {name: i for i, name in enumerate(LOCK_ORDER)}
        for m, ms in self.scans.items():
            qual = f"{cls}.{m}"
            for a in ms.accesses:
                g = guard.get(a.attr)
                if g and not ((a.held | must[m]) & g):
                    kind = "write to" if a.write else "read of"
                    out.append(Finding(
                        self.path, a.node.lineno, CHECKER, qual,
                        f"{kind} {a.attr} (guarded by "
                        f"{sorted(g)}) without holding it (LD1)"))
            for desc, node, held in ms.blocking:
                locks_at = held | may[m]
                if locks_at:
                    out.append(Finding(
                        self.path, node.lineno, CHECKER, qual,
                        f"{desc} while holding {sorted(locks_at)} "
                        "(LD3)"))
            for lock, node, held in ms.acquires:
                locks_at = held | may[m]
                if lock in locks_at:
                    out.append(Finding(
                        self.path, node.lineno, CHECKER, qual,
                        f"possible self-deadlock: acquiring {lock} "
                        "while it may already be held on a caller "
                        "path (LD4)"))
                elif lock in rank and any(
                        rank.get(h, -1) > rank[lock]
                        for h in locks_at):
                    inner = sorted(h for h in locks_at if h in rank
                                   and rank[h] > rank[lock])
                    out.append(Finding(
                        self.path, node.lineno, CHECKER, qual,
                        f"acquiring {lock} while holding {inner} "
                        f"violates the declared "
                        f"{' -> '.join(LOCK_ORDER)} order (LD4)"))
        return out


def guarded_attributes(path: str, node: "ast.ClassDef"
                       ) -> tuple[dict[str, frozenset],
                                  dict[str, frozenset]]:
    """({attr: guard-lock set}, {method: must-held set}) for one
    class, or ({}, {}) when it owns no locks. The lifecycle pass's
    LC4 torn-write rule imports THIS — both passes must agree on
    which attributes are guarded shared state, or a rename would
    silently drop an attribute from one audit but not the other."""
    ca = _ClassAnalysis(path, node)
    if not ca.locks:
        return {}, {}
    ca.scan()
    must, _may = ca.propagate()
    guard, _ld2 = ca.infer_guards(must)
    return guard, must


def check_source(path: str, source: str) -> list[Finding]:
    """Run the lock-discipline rules over every lock-owning class in
    `source` (fixtures and the real roster share this entry point)."""
    tree = ast.parse(source, filename=path)
    out: list[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            out.extend(_ClassAnalysis(path, node).check())
    return out


def check_locks(root: str | None = None) -> list[Finding]:
    if root is None:
        root = default_root()
    out: list[Finding] = []
    for rel in LOCK_ROSTER:
        source, missing = read_rostered(root, rel, CHECKER)
        if missing is not None:
            out.append(missing)
            continue
        out.extend(check_source(rel, source))
    return out


register_pass(Pass(
    id=CHECKER,
    title="cross-thread state must be touched under its inferred "
          "guard, never block while locked, and respect the "
          "_step_lock -> _lock order",
    run=check_locks,
    roster=lambda root: LOCK_ROSTER,
))

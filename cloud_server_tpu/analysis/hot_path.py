"""Hot-path sync/allocation lint (checker id: ``hot-path``).

The serving schedulers pay ONE host<->device sync per iteration (the
device_get of the sampled tokens); everything else in the iteration is
plain host arithmetic on state the scheduler already owns. The QoS
layer (``inference/qos.py``) runs inside that iteration — admission
picks, deficit/virtual-time accounting, token-bucket charges — so its
hot functions must never reintroduce the per-iteration stalls PR 2
removed.

``HOT_PATHS`` registers (file, qualname) pairs; inside each listed
function the lint flags:

  * any use of ``jax`` / ``jnp`` / ``lax`` — device work (dispatches,
    allocations, or implicit transfers) has no business in host-side
    policy code;
  * any use of ``np`` / ``numpy`` — a numpy buffer materialized per
    call is the allocation class this lint means by "allocation-free"
    (Python's own objects — small dicts/lists — are unavoidable and
    cheap; array buffers are not);
  * blocking transfers and syncs: ``device_get``,
    ``block_until_ready``, ``.item()``;
  * host I/O that stalls the scheduler thread: ``print``, ``open``,
    ``input``, ``logging`` calls, ``time.sleep``;
  * ``time.time()`` — the schedulers time with the monotonic clocks
    (``time.monotonic`` / ``time.perf_counter``), which are allowed;
    wall-clock reads are not (NTP steps would corrupt token-bucket
    refill math).

Registered functions are checked for EXISTENCE too: renaming a hot
function without updating the registry fails the gate, so the lint
cannot silently rot.
"""

from __future__ import annotations

import ast

from cloud_server_tpu.analysis.framework import (Finding, Pass,
                                                 collect_functions,
                                                 default_root,
                                                 dotted_name,
                                                 enclosing_class_line,
                                                 read_rostered,
                                                 register_pass)

CHECKER = "hot-path"

# (repo-relative file) -> qualnames whose bodies are per-iteration /
# per-submit hot path. Keep this in sync with the scheduler: anything
# called from step()/submit() on every request or iteration belongs
# here.
HOT_PATHS: dict[str, tuple[str, ...]] = {
    # per-request tracing: the span-RECORD path runs at submit, at
    # request completion, and once per traced iteration — tree
    # building and exports are read-path only and deliberately absent
    "cloud_server_tpu/inference/request_trace.py": (
        "RequestTrace.add_span",
        "RequestTrace.annotate",
        "TraceRecorder.should_sample",
        "TraceRecorder.begin",
        "TraceRecorder.finish",
        # tail-retention verdict: runs inside finish() at every
        # completion that carries a (head or provisional) trace
        "TraceRecorder._tail_reason",
    ),
    # iteration-phase profiler: begin/mark run at every phase
    # boundary of every scheduler iteration (the tightest loop this
    # roster covers — a stray allocation or sync here would taint the
    # very attribution it produces); phases_ms feeds the per-busy-
    # iteration flight record. The summary/export functions
    # (profile_summary, scheduler_chrome_trace) are read-path only
    # and deliberately absent.
    "cloud_server_tpu/inference/iteration_profile.py": (
        "IterationProfiler.begin",
        "IterationProfiler.mark",
        "IterationProfiler.phases_ms",
        "derive_gap_fields",
    ),
    # cache telemetry: the record hooks run inside the allocator's
    # lookup/alloc/release/evict — i.e. inside _start_admissions /
    # _extend_chains / _release_slot on every scheduler iteration that
    # moves pages. The read paths (tenant_stats / top_prefixes /
    # merge_*) are scrape-path only and deliberately absent; sketch
    # compaction (_compact) IS on the roster — it runs amortized
    # inside record_walk and must stay plain dict work.
    "cloud_server_tpu/inference/cache_telemetry.py": (
        "CacheTelemetry.record_walk",
        "CacheTelemetry.record_alloc",
        "CacheTelemetry.record_release",
        "CacheTelemetry.record_saved",
        "CacheTelemetry.record_evict",
        "CacheTelemetry._compact",
        "CacheTelemetry._tenant",
    ),
    # failure-domain layer: FaultPlan.fire/check run per guarded site
    # hit on the scheduler iteration and submit paths (a plan that
    # stalls the scheduler by ACCIDENT would corrupt the very recovery
    # measurements it exists for — maybe_stall/maybe_wedge, whose JOB
    # is blocking, are deliberately absent); the OverloadDetector's
    # observe runs once per busy iteration and level/shed/retry_hint
    # gate every submit
    "cloud_server_tpu/inference/faults.py": (
        "FaultPlan.fire",
        "FaultPlan.check",
        "OverloadDetector.observe",
        "OverloadDetector._effective_locked",
        "OverloadDetector.level",
        "OverloadDetector.shed",
        "OverloadDetector.retry_hint",
    ),
    # SLO tracking: observe() runs at admit / first-token / emit /
    # finish host moments; report/mirror are scrape-path only.
    # exceeds_target feeds the tail-retention verdict at every
    # completion.
    "cloud_server_tpu/inference/slo.py": (
        "ClassSLO.target",
        "_RollingCounts.observe",
        "SLOTracker.resolve_class",
        "SLOTracker.observe",
        "SLOTracker.exceeds_target",
    ),
    # anomaly watchdog: observe_iteration runs once per busy
    # scheduler iteration and observe_request at every completion —
    # both on caller-passed clocks (zero clock reads of their own);
    # active_count gates the tail-retention verdict at completion.
    # The read paths (stats / events / merge_anomaly_stats) are
    # scrape-path only and deliberately absent.
    "cloud_server_tpu/inference/anomaly.py": (
        "AnomalyWatchdog.observe_iteration",
        "AnomalyWatchdog.observe_request",
        "AnomalyWatchdog.active_count",
        "AnomalyWatchdog._update_rule",
        "AnomalyWatchdog._shift",
    ),
    # adaptive speculation control: planning (draft_len) and feedback
    # (observe / on_plain_dispatch) run once per dispatch / committed
    # round inside the scheduler iteration; draft_lengths feeds the
    # per-busy-iteration flight record. resolve_controller (construction,
    # may open a config file) is deliberately absent.
    "cloud_server_tpu/inference/spec_control.py": (
        "SpecController.on_admit",
        "SpecController.on_release",
        "SpecController.draft_len",
        "SpecController.observe",
        "SpecController.on_plain_dispatch",
        "SpecController.accept_rate",
        "SpecController.draft_lengths",
    ),
    # replica router: _pick/submit run once per request on the client
    # thread while holding the router lock (a stall here blocks every
    # concurrent submitter), and the post-merge ratio recomputes
    # (fair-share / accept-rate / SLO gauges) run on the scrape path
    # but iterate the whole fleet per call
    "cloud_server_tpu/inference/router.py": (
        "ReplicatedRouter._pick",
        "ReplicatedRouter.submit",
        "ReplicatedRouter.num_active",
        "ReplicatedRouter.num_pending",
        "ReplicatedRouter.metrics_snapshot",
        "ReplicatedRouter.tenant_stats",
        "ReplicatedRouter.speculation_stats",
        "ReplicatedRouter.cache_stats",
        # disaggregation role planner: runs inside every _pick/submit
        # under the router lock, same stall blast radius
        "ReplicatedRouter._role_candidates",
        "ReplicatedRouter._prefill_load",
        "ReplicatedRouter._plan_roles",
    ),
    # live migration: the ledger's record hooks run on the export /
    # import paths while the SOURCE or DESTINATION server's step lock
    # is held (a stall there freezes that replica's scheduler), and
    # drain_flight_deltas runs once per busy iteration inside
    # _record_iteration to feed the flight recorder's migrated_in/out
    # counts. The snapshot helpers run under the same locks. The
    # device-touching export/import bodies live in paged_server (and
    # are covered by the dispatch-discipline pass), NOT here — this
    # module must stay pure host bookkeeping.
    "cloud_server_tpu/inference/migration.py": (
        "MigrationLedger.record_export_start",
        "MigrationLedger.record_export_done",
        "MigrationLedger.record_export_failed",
        "MigrationLedger.record_import_start",
        "MigrationLedger.record_import_done",
        "MigrationLedger.record_import_failed",
        "MigrationLedger.drain_flight_deltas",
        "MigrationSnapshot.remaining_new_tokens",
        "MigrationSnapshot.n_kv_pages",
    ),
    "cloud_server_tpu/inference/qos.py": (
        "TokenBucket._refill",
        "TokenBucket.level",
        "TokenBucket.try_consume",
        "TokenBucket.charge",
        "TokenBucket.retry_after",
        "TenantRegistry.resolve",
        "TenantRegistry.priority_rank",
        "TenantRegistry.priority_class",
        "TenantRegistry.weight",
        "TenantRegistry.default_deadline",
        "TenantRegistry.victim_rank",
        "TenantRegistry._decay_recent",
        "TenantRegistry.gate_submit",
        "TenantRegistry.on_pending_removed",
        "TenantRegistry.on_requeue",
        "TenantRegistry.next_admission_index",
        "TenantRegistry._in_budget",
        "TenantRegistry.charge_admission",
        "TenantRegistry.order_jobs",
        "TenantRegistry.charge_prefill",
        "TenantRegistry.charge_generated",
        "TenantRegistry.charge_speculation",
        # per-busy-iteration flight-recorder gauge
        "TenantRegistry.fair_shares",
        "TenantRegistry._fair_shares_locked",
    ),
    # scenario replay: tick()/_fire() interleave with scheduler step()
    # pumping on the serving thread — the caller owns time (tick takes
    # `now`), so a clock read, sleep, or log line here would skew the
    # very replay timings the harness measures. run()/result() are the
    # wall-clock convenience/read paths and deliberately absent.
    "cloud_server_tpu/scenarios/replay.py": (
        "ReplayDriver.tick",
        "ReplayDriver._fire",
    ),
    # autoscaler decision path: evaluate()/_burn_signal() run per poll
    # under the autoscaler lock while submit threads contend for the
    # router — pure decision on a caller-passed clock. The actuation
    # paths (_scale_up/_scale_down, which legitimately log and drain)
    # are deliberately absent.
    "cloud_server_tpu/scenarios/autoscaler.py": (
        "SLOBurnAutoscaler.evaluate",
        "SLOBurnAutoscaler._burn_signal",
    ),
    "cloud_server_tpu/utils/serving_metrics.py": (
        "Counter.inc",
        "Gauge.set",
        "Histogram.observe",
        "FlightRecorder.record",
        "ServingMetrics.observe_submit",
        "ServingMetrics.observe_admit",
        "ServingMetrics.observe_emit",
        "ServingMetrics.observe_requeue",
        "ServingMetrics.observe_finish",
    ),
}

_DEVICE_ROOTS = {"jax", "jnp", "lax"}
_NUMPY_ROOTS = {"np", "numpy"}
_SYNC_ATTRS = {"device_get", "block_until_ready", "item"}
_IO_CALLS = {"print", "open", "input"}
_LOG_ROOTS = {"logging", "logger", "log"}


_dotted = dotted_name


def _check_function(path: str, qual: str,
                    fn: ast.FunctionDef) -> list[Finding]:
    out: list[Finding] = []

    def flag(node: ast.AST, msg: str) -> None:
        out.append(Finding(path, getattr(node, "lineno", fn.lineno),
                           CHECKER, qual, msg))

    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            if node.id in _DEVICE_ROOTS:
                flag(node, f"device-framework use ({node.id}.*) on the "
                           "host hot path")
            elif node.id in _NUMPY_ROOTS:
                flag(node, f"numpy buffer work ({node.id}.*) on the "
                           "host hot path (allocation per call)")
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            mod = getattr(node, "module", None) or ""
            names = {a.name.split(".")[0] for a in node.names}
            roots = _DEVICE_ROOTS | _NUMPY_ROOTS
            if mod.split(".")[0] in roots or names & roots:
                flag(node, "device/numpy import inside a hot-path "
                           "function")
        elif isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name is None:
                continue
            leaf = name.rsplit(".", 1)[-1]
            root = name.split(".", 1)[0]
            if leaf in _SYNC_ATTRS:
                flag(node, f"blocking sync/transfer call {name}()")
            elif name in _IO_CALLS:
                flag(node, f"host I/O call {name}() stalls the "
                           "scheduler thread")
            elif name == "time.time":
                flag(node, "wall-clock time.time() — use the monotonic "
                           "clocks (time.monotonic / perf_counter)")
            elif name == "time.sleep" or leaf == "sleep":
                flag(node, f"sleep call {name}() on the hot path")
            elif root in _LOG_ROOTS or (
                    "." in name and name.rsplit(".", 2)[-2] in _LOG_ROOTS):
                flag(node, f"logging call {name}() on the hot path")
    return out


def check_source(path: str, source: str,
                 qualnames: tuple[str, ...]) -> list[Finding]:
    """Lint `qualnames` inside `source`; missing qualnames are findings
    too (the registry must not rot when functions are renamed)."""
    tree = ast.parse(source, filename=path)
    found, classes = collect_functions(tree)
    out: list[Finding] = []
    for qual in qualnames:
        fn = found.get(qual)
        if fn is None:
            # anchored at the enclosing class when it exists, so the
            # finding lands where the rename happened — not at line 1
            line = enclosing_class_line(classes, qual)
            out.append(Finding(path, line, CHECKER, qual,
                               "registered hot-path function not found "
                               "(renamed? update HOT_PATHS)"))
            continue
        out.extend(_check_function(path, qual, fn))
    return out


def check_hot_paths(root: str | None = None) -> list[Finding]:
    """Run the lint over every registered file. `root` defaults to
    the repository root."""
    if root is None:
        root = default_root()
    out: list[Finding] = []
    for rel, quals in HOT_PATHS.items():
        source, missing = read_rostered(root, rel, CHECKER)
        if missing is not None:
            out.append(missing)
            continue
        out.extend(check_source(rel, source, quals))
    return out


register_pass(Pass(
    id=CHECKER,
    title="per-iteration scheduler code must stay free of device work, "
          "blocking syncs, numpy allocation, wall-clock reads, and "
          "host I/O",
    run=check_hot_paths,
    roster=lambda root: tuple(HOT_PATHS),
))

"""Dispatch-discipline pass (checker id: ``dispatch-discipline``).

PR 2/9's load-bearing invariant: each scheduler iteration runs ONE
fused jitted dispatch and pays ONE host<->device sync (the
``device_get`` of the sampled tokens). The runtime regression tests
count dispatches on one driven path; this pass pins the invariant
statically across the whole scheduler loop of both servers.

Rules:

  * ``DD1 jit inventory`` — jitted callables are auto-discovered in
    each audited server file (``name = partial(jax.jit, ...)``
    assignments and ``@partial(jax.jit, ...)`` / ``@jax.jit``
    decorations), along with their ``static_argnames``.
  * ``DD2 sanctioned sync`` — ``jax.device_get`` may appear ONLY in
    the functions listed in ``SANCTIONED_SYNCS`` (the per-iteration
    commit points). Any other ``device_get`` on the scheduler loop,
    and ANY ``block_until_ready`` / ``.item()`` /
    ``.copy_to_host_async()``, flags. Each sanctioned function must
    exist and actually contain a ``device_get`` (sanction rot is a
    finding too). Async host->device feeds (``jnp.asarray`` /
    ``device_put``) are deliberately NOT flagged: they overlap with
    compute and are the dispatch input path.
  * ``DD3 host-policy purity`` — modules in ``HOST_POLICY_MODULES``
    (admission policy, SLO math, tracing, speculation control,
    metrics) must never import or touch ``jax`` / ``jnp`` / ``lax``;
    device work belongs to the servers, which ARE the allowlist.
  * ``DD4 static-arg boundedness`` — every value flowing into a
    jitted callable's static argument from a scheduler-loop function
    must come from a STATICALLY BOUNDED set, because each distinct
    value compiles a new program variant (the compile-variant
    invariant PR 9's ``{0, spec_drafts}`` draft-width quantization
    depends on). Bounded means: constants, ``self.*`` configuration,
    boolean expressions, callee parameters declared ``bool``, the
    audited bucketing helpers in ``BOUNDED_HELPERS`` (power-of-two
    rounding / bucket tables / round planners) — composed through
    arithmetic, min/max, and conditionals — and the reviewed
    ``plan.*`` fields in ``PLAN_BOUNDED_FIELDS`` (the async
    scheduler's ``_launch_plan`` replays statics the planner already
    computed through those same bounded helpers). A raw ``len(...)``,
    a request field, or any other data-dependent value flags.
  * ``DD5 overlap write-safety`` — the async double-buffered
    scheduler plans iteration N+1 WHILE iteration N's dispatch is in
    flight. A page released during that window can be re-allocated to
    a new admission while the device still writes it, so the
    functions in ``OVERLAP_PLAN_FUNCS`` (the plan/launch path and the
    deferred sweep) must never reach — directly or transitively
    through same-class helpers — any of the page-releasing /
    slot-teardown functions in ``PAGE_RELEASING_FUNCS``. Releases
    belong to the commit (``_commit_inflight`` / ``_apply_reaps``)
    and to the sequential paths, which only run with nothing in
    flight.

Stdlib-only (ast); never imports jax or the serving stack.
"""

from __future__ import annotations

import ast

from cloud_server_tpu.analysis.framework import (Finding, Pass,
                                                 collect_functions,
                                                 default_root,
                                                 dotted_name,
                                                 enclosing_class_line,
                                                 read_rostered,
                                                 register_pass)

CHECKER = "dispatch-discipline"

# Scheduler-loop functions per server file: everything reachable from
# step() on every iteration. The jit call sites and sync sites this
# pass polices all live here.
SCHEDULER_LOOPS: dict[str, tuple[str, ...]] = {
    "cloud_server_tpu/inference/paged_server.py": (
        "PagedInferenceServer.step",
        "PagedInferenceServer._step_sequential",
        "PagedInferenceServer.serve_forever",
        "PagedInferenceServer._step_overlap",
        "PagedInferenceServer._plan_iteration",
        "PagedInferenceServer._launch_plan",
        "PagedInferenceServer._commit_inflight",
        "PagedInferenceServer._overlap_sweep",
        "PagedInferenceServer._apply_reaps",
        "PagedInferenceServer._extend_chains_planned",
        "PagedInferenceServer._build_prefill_group",
        "PagedInferenceServer._select_prefill",
        "PagedInferenceServer._expire_pending",
        "PagedInferenceServer._sweep_cancelled",
        "PagedInferenceServer._start_admissions",
        "PagedInferenceServer._run_one_chunk",
        "PagedInferenceServer._decode_dispatch",
        "PagedInferenceServer._mixed_dispatch",
        "PagedInferenceServer._commit_decode_rows",
        "PagedInferenceServer._record_iteration",
        "PagedInferenceServer._stage_decode_spans",
        "PagedInferenceServer._stage_spec_stats",
        "PagedInferenceServer._gather_decode_rows",
        "PagedInferenceServer._spec_plan",
        "PagedInferenceServer._pad_limits",
        "PagedInferenceServer._drafted_rows",
        "PagedInferenceServer._chunk_rounds",
        "PagedInferenceServer._mixed_rounds",
        "PagedInferenceServer._extend_chains",
        "PagedInferenceServer._preempt_youngest",
        "PagedInferenceServer._rem_bucket",
        "PagedInferenceServer._ensure_penalty_state",
        "PagedInferenceServer._emit",
        "PagedInferenceServer._finish",
        "PagedInferenceServer._release_slot",
        "PagedInferenceServer._committed",
        "PagedInferenceServer._next_rng",
        # live-migration path: off the step loop (it runs on router /
        # drain threads), but policed by the same sync discipline — the
        # export owns its ONE sanctioned device_get (below), and the
        # import must stay async (its scatter is a dispatch; jnp.asarray
        # feeds are the input path DD2 deliberately allows)
        "PagedInferenceServer.migrate_export",
        "PagedInferenceServer.migrate_salvage",
        "PagedInferenceServer._export_request_locked",
        "PagedInferenceServer._build_snapshot",
        "PagedInferenceServer._evacuate_request_locked",
        "PagedInferenceServer._evacuate",
        "PagedInferenceServer.migrate_import",
        "PagedInferenceServer._import_pages",
        # disaggregation handoff: the prefetch runs on the iteration
        # path right before the mixed dispatch (its copy_to_host_async
        # STARTS are pragma-sanctioned — they are not host syncs), the
        # drain runs at the end of every step outside the step lock,
        # and pending_prefill_tokens is the router's prefill-load read
        "PagedInferenceServer._handoff_prefetch",
        "PagedInferenceServer._drain_handoff_ready",
        "PagedInferenceServer.pending_prefill_tokens",
    ),
    "cloud_server_tpu/inference/server.py": (
        "InferenceServer.step",
        "InferenceServer._step_locked",
        "InferenceServer._step_locked_overlap",
        "InferenceServer._commit_decode_chunk",
        "InferenceServer._launch_decode",
        "InferenceServer.serve_forever",
        "InferenceServer._sweep_cancelled",
        "InferenceServer._admit_pending",
        "InferenceServer._use_prefix",
        "InferenceServer._pad_group",
        "InferenceServer._ensure_penalty_state",
        "InferenceServer._group_rows",
        "InferenceServer._rows_mode",
        "InferenceServer._admit_group",
        "InferenceServer._admit_group_plain",
        "InferenceServer._admit_group_prefixed",
        "InferenceServer._chunk_len",
        "InferenceServer._emit",
        "InferenceServer._finish",
        "InferenceServer._next_rng",
    ),
}

# The ONE sanctioned per-iteration host sync per dispatch path: these
# are the commit points where the sampled tokens come home. Everything
# else on the loop must stay async.
SANCTIONED_SYNCS: dict[str, tuple[str, ...]] = {
    "cloud_server_tpu/inference/paged_server.py": (
        "PagedInferenceServer._run_one_chunk",
        "PagedInferenceServer._decode_dispatch",
        "PagedInferenceServer._mixed_dispatch",
        # async scheduler: the launch-ahead dispatch's commit point —
        # still ONE device_get per committed iteration; _launch_plan
        # itself must stay sync-free (DD2 covers it like every other
        # loop function)
        "PagedInferenceServer._commit_inflight",
        # live migration: the request export's KV gather — ONE sync per
        # migration, at the commit point (inflight work committed
        # first), under the step lock and off the plan path, so DD5's
        # overlap window never sees it
        "PagedInferenceServer._export_request_locked",
    ),
    "cloud_server_tpu/inference/server.py": (
        "InferenceServer._admit_group",
        "InferenceServer._step_locked",
        "InferenceServer._commit_decode_chunk",
    ),
}

# DD5: the async scheduler's plan/launch path — everything that runs
# while a dispatch may be in flight — and the page-releasing functions
# it must never reach. Transitive through same-class helper calls.
OVERLAP_PLAN_FUNCS: dict[str, tuple[str, ...]] = {
    "cloud_server_tpu/inference/paged_server.py": (
        "PagedInferenceServer._plan_iteration",
        "PagedInferenceServer._extend_chains_planned",
        "PagedInferenceServer._overlap_sweep",
        "PagedInferenceServer._launch_plan",
        "PagedInferenceServer._build_prefill_group",
        "PagedInferenceServer._select_prefill",
        # the handoff KV prefetch runs inside _launch_plan while the
        # PREVIOUS dispatch may still be in flight: it reads committed
        # pages and starts D2H copies but must never release a page —
        # and must never reach the export path (whose device_get is
        # sanctioned only OFF the plan path)
        "PagedInferenceServer._handoff_prefetch",
    ),
}
PAGE_RELEASING_FUNCS = frozenset({
    "_release_slot", "_preempt_youngest", "_finish", "_extend_chains",
    "_fail_all", "_sweep_cancelled",
    # allocator page release (self.allocator.release / the lock-free
    # variants); plan-path code may alloc, never release
    "release",
})

# DD4: reviewed fields of the async scheduler's _Plan snapshot that
# are bounded BY CONSTRUCTION — _plan_iteration computes them through
# the same audited helpers this pass already trusts (n_rounds via the
# _mixed_rounds/_chunk_rounds pow2 planners, g_iter via _spec_plan's
# {0, spec_drafts} quantization) — so _launch_plan replaying them into
# the jits' static arguments cannot mint new compile variants. Adding
# a field here is a reviewed decision, exactly like BOUNDED_HELPERS.
PLAN_BOUNDED_FIELDS = frozenset({"n_rounds", "g_iter"})

# Pure host-side policy modules: scheduling decisions, accounting,
# telemetry. The servers are the only modules allowed to touch jax.
HOST_POLICY_MODULES: tuple[str, ...] = (
    "cloud_server_tpu/inference/qos.py",
    "cloud_server_tpu/inference/faults.py",
    "cloud_server_tpu/inference/migration.py",
    "cloud_server_tpu/inference/slo.py",
    "cloud_server_tpu/inference/request_trace.py",
    "cloud_server_tpu/inference/spec_control.py",
    "cloud_server_tpu/inference/iteration_profile.py",
    "cloud_server_tpu/inference/cache_telemetry.py",
    "cloud_server_tpu/inference/anomaly.py",
    "cloud_server_tpu/utils/serving_metrics.py",
    # scenario harness: workload generation, replay, the discrete-event
    # simulator, and the autoscaler are all pure host policy — the
    # simulator MODELS device iterations from fitted flight-record
    # costs, it must never run one
    "cloud_server_tpu/scenarios/workload.py",
    "cloud_server_tpu/scenarios/replay.py",
    "cloud_server_tpu/scenarios/simulator.py",
    "cloud_server_tpu/scenarios/autoscaler.py",
)

# Call leaves whose results are statically bounded REGARDLESS of their
# arguments — the audited bucketing/planning helpers. Adding a name
# here is a reviewed decision: the helper must quantize its output to
# a fixed set (powers of two, a bucket table, {0, spec_drafts}).
BOUNDED_HELPERS = {
    "_pad_pow2",       # next power of two, log2-many values
    "_bucket",         # fixed bucket table lookup
    "_rem_bucket",     # bucket table / prefill_chunk multiples
    "_chunk_rounds",   # power-of-two round planner (paged)
    "_chunk_len",      # power-of-two round planner (contiguous)
    "_mixed_rounds",   # power-of-two round planner (mixed budget)
    "_spec_plan",      # draft width quantized to {0, spec_drafts}
    "_rows_mode",      # (bool, bool)
    "_group_rows",     # (..., bool, bool)
    "bool",
}
# bounded only when every argument is bounded (len is NOT here: a
# data-dependent length is exactly the unbounded source this rule
# exists to catch — route it through a bucketing helper instead)
_ARG_BOUNDED_CALLS = {"min", "max", "int", "abs", "round"}

_SYNC_LEAVES = {"block_until_ready", "item", "copy_to_host_async"}
_DEVICE_ROOTS = {"jax", "jnp", "lax"}


_dotted = dotted_name


def _self_rooted(node: ast.AST) -> bool:
    while isinstance(node, ast.Attribute):
        node = node.value
    return isinstance(node, ast.Name) and node.id == "self"


# -- DD1: jit inventory -----------------------------------------------------

def _partial_jit_call(node: ast.AST) -> ast.Call | None:
    """The `partial(jax.jit, ...)` Call, from either `partial(...)`
    itself or a `partial(...)(core)` application."""
    if not isinstance(node, ast.Call):
        return None
    name = _dotted(node.func)
    if name in ("partial", "functools.partial"):
        if node.args and _dotted(node.args[0]) in ("jax.jit", "jit"):
            return node
        return None
    # application form: partial(jax.jit, ...)(core_fn)
    return _partial_jit_call(node.func)


def _static_names(pcall: ast.Call) -> tuple[str, ...] | None:
    """Declared static_argnames; () when none are declared; None when
    the declaration exists but is NOT a literal — boundedness cannot
    be verified then, which must be a finding, not a silent skip."""
    for kw in pcall.keywords:
        if kw.arg == "static_argnames":
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                if all(isinstance(e, ast.Constant)
                       for e in kw.value.elts):
                    return tuple(e.value for e in kw.value.elts)
                return None
            if isinstance(kw.value, ast.Constant):
                return (kw.value.value,)
            return None
    return ()


def _bool_statics(fn: ast.AST | None) -> set[str]:
    """Static params annotated/defaulted bool on the traced callee:
    at most two compile variants each — intrinsically bounded."""
    out: set[str] = set()
    if fn is None:
        return out
    args = fn.args
    pairs = list(zip(args.kwonlyargs, args.kw_defaults))
    n_def = len(args.defaults)
    pos = args.posonlyargs + args.args
    pairs += list(zip(pos[len(pos) - n_def:], args.defaults))
    for a, default in pairs:
        ann = a.annotation
        if (isinstance(ann, ast.Name) and ann.id == "bool") or \
                isinstance(getattr(default, "value", None), bool):
            out.add(a.arg)
    return out


class _JitInfo:
    __slots__ = ("name", "statics", "bool_statics", "params", "node")

    def __init__(self, name, statics, bool_statics, params, node):
        self.name = name
        self.statics = statics
        self.bool_statics = bool_statics
        self.params = params          # positional-capable param names
        self.node = node


def _positional_params(fn: ast.AST | None) -> tuple[str, ...]:
    if fn is None:
        return ()
    return tuple(a.arg for a in fn.args.posonlyargs + fn.args.args)


def inventory_jits(tree: ast.Module) -> dict[str, _JitInfo]:
    """Every jitted callable declared in the module, by name."""
    defs = {n.name: n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    out: dict[str, _JitInfo] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            pcall = _partial_jit_call(node.value)
            if pcall is None:
                continue
            name = node.targets[0].id
            core = None
            if isinstance(node.value, ast.Call) and node.value.args:
                core = defs.get(_dotted(node.value.args[0]) or "")
            out[name] = _JitInfo(name, _static_names(pcall),
                                 _bool_statics(core),
                                 _positional_params(core), node)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _dotted(dec) in ("jax.jit", "jit"):
                    out[node.name] = _JitInfo(node.name, (), set(),
                                              _positional_params(node),
                                              node)
                    break
                pcall = _partial_jit_call(dec)
                if pcall is not None:
                    out[node.name] = _JitInfo(
                        node.name, _static_names(pcall),
                        _bool_statics(node),
                        _positional_params(node), node)
                    break
    return out


# -- DD4: static-arg boundedness --------------------------------------------

class _Boundedness:
    """Optimistic per-function classifier: local names start bounded
    and are demoted whenever any assignment feeds them an unbounded
    expression, to fixpoint. Function parameters are unbounded."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        params = {a.arg for a in fn.args.posonlyargs + fn.args.args
                  + fn.args.kwonlyargs}
        params.discard("self")
        self.unbounded: set[str] = set(params)
        self.assigns: list[tuple[list, ast.AST]] = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                self.assigns.append((node.targets, node.value))
            elif isinstance(node, ast.AugAssign):
                synth = ast.BinOp(left=node.target, op=node.op,
                                  right=node.value)
                self.assigns.append(([node.target], synth))
            elif isinstance(node, ast.AnnAssign) and node.value:
                self.assigns.append(([node.target], node.value))
            elif isinstance(node, ast.NamedExpr):
                # walrus: `(n := expr)` binds like an assignment
                self.assigns.append(([node.target], node.value))
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                self._demote_target(node.target)
            elif isinstance(node, ast.comprehension):
                self._demote_target(node.target)
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if item.optional_vars is not None:
                        self._demote_target(item.optional_vars)
        changed = True
        while changed:
            changed = False
            for targets, value in self.assigns:
                for tgt, expr in self._pair(targets, value):
                    name = tgt.id if isinstance(tgt, ast.Name) else None
                    if name and name not in self.unbounded \
                            and not self.bounded(expr):
                        self.unbounded.add(name)
                        changed = True

    def _demote_target(self, tgt: ast.AST) -> None:
        for n in ast.walk(tgt):
            if isinstance(n, ast.Name):
                self.unbounded.add(n.id)

    def _pair(self, targets, value):
        """(target, value-expr) pairs; tuple targets fed by a bounded
        helper call (e.g. `a, b = self._spec_plan(...)`) bind every
        name to that call."""
        for tgt in targets:
            if isinstance(tgt, (ast.Tuple, ast.List)):
                if isinstance(value, (ast.Tuple, ast.List)) \
                        and len(value.elts) == len(tgt.elts):
                    yield from zip(tgt.elts, value.elts)
                else:
                    for e in tgt.elts:
                        yield e, value
            else:
                yield tgt, value

    def bounded(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Name):
            return node.id not in self.unbounded
        if isinstance(node, ast.Attribute):
            if (isinstance(node.value, ast.Name)
                    and node.value.id == "plan"
                    and node.attr in PLAN_BOUNDED_FIELDS):
                return True  # reviewed _Plan statics (see the constant)
            return _self_rooted(node)  # init-time configuration
        if isinstance(node, (ast.Compare, ast.BoolOp)):
            return True  # boolean-valued: at most two variants
        if isinstance(node, ast.UnaryOp):
            return isinstance(node.op, ast.Not) \
                or self.bounded(node.operand)
        if isinstance(node, ast.BinOp):
            return self.bounded(node.left) and self.bounded(node.right)
        if isinstance(node, ast.IfExp):
            return self.bounded(node.body) and self.bounded(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return all(self.bounded(e) for e in node.elts)
        if isinstance(node, ast.Call):
            leaf = (_dotted(node.func) or "?").rsplit(".", 1)[-1]
            if leaf in BOUNDED_HELPERS:
                return True
            if leaf in _ARG_BOUNDED_CALLS:
                return all(self.bounded(a) for a in node.args)
            return False
        return False


# -- the pass ---------------------------------------------------------------

def check_scheduler_source(path: str, source: str,
                           loop_quals: tuple[str, ...],
                           sanctioned: tuple[str, ...]) -> list[Finding]:
    """DD1/DD2/DD4 over one server module."""
    tree = ast.parse(source, filename=path)
    jits = inventory_jits(tree)
    found, classes = collect_functions(tree)
    out: list[Finding] = []

    def missing(qual: str, what: str) -> None:
        out.append(Finding(path, enclosing_class_line(classes, qual),
                           CHECKER, qual,
                           f"{what} (renamed? update the "
                           "dispatch-discipline roster)"))

    for qual in sanctioned:
        fn = found.get(qual)
        if fn is None:
            missing(qual, "sanctioned-sync function not found")
        elif not any(isinstance(n, ast.Call)
                     and (_dotted(n.func) or "").endswith("device_get")
                     for n in ast.walk(fn)):
            out.append(Finding(
                path, fn.lineno, CHECKER, qual,
                "sanctioned-sync function no longer contains a "
                "device_get — the sanction list has rotted"))

    for qual in loop_quals:
        fn = found.get(qual)
        if fn is None:
            missing(qual, "scheduler-loop function not found")
            continue
        bound = None  # built lazily: most loop functions call no jits
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            leaf = (name or "?").rsplit(".", 1)[-1]
            if leaf == "device_get" and qual not in sanctioned:
                out.append(Finding(
                    path, node.lineno, CHECKER, qual,
                    "device sync device_get() outside the sanctioned "
                    "per-iteration commit points (DD2)"))
            elif leaf in _SYNC_LEAVES:
                out.append(Finding(
                    path, node.lineno, CHECKER, qual,
                    f"device sync {name or leaf}() on the scheduler "
                    "loop (DD2)"))
            ji = jits.get(leaf) if name == leaf else None
            if ji is None:
                continue
            if ji.statics is None:
                out.append(Finding(
                    path, node.lineno, CHECKER, qual,
                    f"static_argnames of {ji.name} is not a literal "
                    "— static-argument boundedness cannot be "
                    "verified (DD4)"))
                continue
            if not ji.statics:
                continue
            if bound is None:
                bound = _Boundedness(fn)

            def unbounded(argname, expr):
                out.append(Finding(
                    path, expr.lineno, CHECKER, qual,
                    f"static argument {argname!r} of {ji.name} fed "
                    "from a statically UNBOUNDED expression — every "
                    "distinct value compiles a new program variant "
                    "(DD4)"))

            # statics can ride POSITIONALLY too: map call positions
            # onto the traced callee's parameter names (a *splat makes
            # later positions unknowable — stop mapping there, the
            # remaining statics arrive as keywords or defaults)
            for i, arg in enumerate(node.args):
                if isinstance(arg, ast.Starred):
                    break
                if i < len(ji.params) \
                        and ji.params[i] in ji.statics \
                        and ji.params[i] not in ji.bool_statics \
                        and not bound.bounded(arg):
                    unbounded(ji.params[i], arg)
            for kw in node.keywords:
                if kw.arg is None:
                    # **splat: statics may hide inside — opaque to
                    # this analysis, so it is a finding by itself
                    out.append(Finding(
                        path, kw.value.lineno, CHECKER, qual,
                        f"**-splat into jitted {ji.name} — static "
                        "arguments cannot be verified through it "
                        "(DD4)"))
                    continue
                if kw.arg not in ji.statics \
                        or kw.arg in ji.bool_statics:
                    continue
                if not bound.bounded(kw.value):
                    unbounded(kw.arg, kw.value)
    return out


def check_overlap_source(path: str, source: str,
                         plan_quals: tuple[str, ...]) -> list[Finding]:
    """DD5 over one server module: no page-releasing function is
    reachable from the overlap plan path — directly, or transitively
    through same-class ``self.*`` helper calls."""
    tree = ast.parse(source, filename=path)
    found, classes = collect_functions(tree)
    out: list[Finding] = []

    def self_calls(fn: ast.AST):
        """(leaf name, node) for every self.X(...) / X(...) call."""
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if name is None:
                continue
            yield name.rsplit(".", 1)[-1], name, node

    for qual in plan_quals:
        fn = found.get(qual)
        if fn is None:
            out.append(Finding(
                path, enclosing_class_line(classes, qual), CHECKER,
                qual, "overlap-plan function not found (renamed? "
                      "update OVERLAP_PLAN_FUNCS)"))
            continue
        cls = qual.rsplit(".", 1)[0]
        seen: set[str] = set()
        stack: list[tuple[str, ast.AST]] = [(qual, fn)]
        while stack:
            cur_qual, cur_fn = stack.pop()
            if cur_qual in seen:
                continue
            seen.add(cur_qual)
            for leaf, name, node in self_calls(cur_fn):
                if leaf in PAGE_RELEASING_FUNCS:
                    out.append(Finding(
                        path, node.lineno, CHECKER, qual,
                        f"overlap-plan path reaches page-releasing "
                        f"{name}() (via {cur_qual}) while a dispatch "
                        "may be in flight — releases belong to the "
                        "commit (DD5)"))
                    continue
                callee_qual = f"{cls}.{leaf}"
                callee = found.get(callee_qual)
                if callee is not None and name.startswith("self."):
                    stack.append((callee_qual, callee))
    return out


def check_host_policy_source(path: str, source: str) -> list[Finding]:
    """DD3: no jax/jnp/lax anywhere in a host-policy module."""
    tree = ast.parse(source, filename=path)
    out: list[Finding] = []
    seen: set[int] = set()

    def flag(node: ast.AST, msg: str) -> None:
        if node.lineno not in seen:
            seen.add(node.lineno)
            out.append(Finding(path, node.lineno, CHECKER, "", msg))

    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            mod = (getattr(node, "module", None) or "").split(".")[0]
            names = {a.name.split(".")[0] for a in node.names}
            hit = ({mod} | names) & _DEVICE_ROOTS
            if hit:
                flag(node, f"host-policy module imports {sorted(hit)} "
                           "— device work belongs to the servers (DD3)")
        elif isinstance(node, ast.Name) and node.id in _DEVICE_ROOTS:
            flag(node, f"host-policy module touches {node.id}.* — "
                       "device work belongs to the servers (DD3)")
    return out


def check_dispatch(root: str | None = None) -> list[Finding]:
    if root is None:
        root = default_root()
    out: list[Finding] = []
    for rel, quals in SCHEDULER_LOOPS.items():
        source, missing = read_rostered(root, rel, CHECKER)
        if missing is not None:
            out.append(missing)
            continue
        out.extend(check_scheduler_source(
            rel, source, quals, SANCTIONED_SYNCS.get(rel, ())))
        plan_quals = OVERLAP_PLAN_FUNCS.get(rel)
        if plan_quals:
            out.extend(check_overlap_source(rel, source, plan_quals))
    for rel in HOST_POLICY_MODULES:
        source, missing = read_rostered(root, rel, CHECKER)
        if missing is not None:
            out.append(missing)
            continue
        out.extend(check_host_policy_source(rel, source))
    return out


register_pass(Pass(
    id=CHECKER,
    title="one sanctioned device_get per scheduler iteration, jax-free "
          "host-policy modules, statically bounded jit static "
          "arguments, and a release-free overlap plan path",
    run=check_dispatch,
    roster=lambda root: tuple(SCHEDULER_LOOPS) + HOST_POLICY_MODULES,
))

"""Multi-pass static-analysis framework for the serving stack.

The serving stack's load-bearing invariants — ONE fused dispatch + ONE
host sync per scheduler iteration, and the `_lock` / `_step_lock`
discipline that keeps client threads and the scheduler thread off each
other's state — are enforced at runtime only on the paths the
regression tests happen to drive. The passes registered here enforce
them statically, over every registered file, on every test run.

Pieces:

  * ``Finding`` — the one result model every pass emits:
    ``path:line``, the checker id, the symbol it fired in, and a
    message. Paths are repo-relative so findings are stable across
    checkouts.
  * ``Pass`` / ``register_pass`` — the registry. A pass is a stable
    checker id, a ``run(root) -> [Finding]`` callable, and a
    ``roster(root)`` callable naming the repo-relative files it
    audits (the suppression scanner walks the union of all rosters).
  * Inline suppressions — ``# analysis: allow[<checker>] <reason>``.
    The reason is MANDATORY: a reason-less pragma is itself a finding
    (checker id ``pragma``), so an exception can never be waved
    through silently. A pragma suppresses findings of that checker on
    its own line; a pragma on a comment-only line also covers the
    next line (for statements too long to carry a trailing comment).
  * ``run_analysis`` — run selected passes, apply suppressions, and
    return a ``Report``; ``render_text`` / ``report_json`` are the
    two reporters the CLI (``__main__``) exposes.

Everything here is stdlib-only (ast + re) and never imports jax,
numpy, or the serving stack: the gate runs inside every test process,
so it must be fast and must not spend any of the process's
vm.max_map_count budget on an XLA backend it never uses.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Callable, Iterable

# The implicit checker id carried by reason-less-pragma findings. Not
# a registered pass — it exists only as a finding namespace (and a
# documented id in docs/analysis.md) and cannot be suppressed.
PRAGMA_CHECKER = "pragma"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One static-analysis result, shared by every pass."""

    path: str       # repo-relative file
    line: int
    checker: str    # stable checker id ("hot-path", "lock-discipline", ...)
    symbol: str     # qualname / attribute the finding is about ("" if n/a)
    message: str

    def __str__(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: [{self.checker}]{sym} " \
               f"{self.message}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Pass:
    """A registered checker: id must be stable (docs, pragmas, and the
    ``--checker`` CLI flag all key on it)."""

    id: str
    title: str                                  # one-line, for docs/CLI
    run: Callable[[str], list]                  # root -> [Finding]
    roster: Callable[[str], tuple]              # root -> repo-rel files


_REGISTRY: dict[str, Pass] = {}


def register_pass(p: Pass) -> Pass:
    if p.id in _REGISTRY:
        raise ValueError(f"checker id {p.id!r} registered twice")
    if p.id == PRAGMA_CHECKER:
        raise ValueError(f"checker id {PRAGMA_CHECKER!r} is reserved "
                         "for reason-less-pragma findings")
    _REGISTRY[p.id] = p
    return p


def registered_passes() -> dict[str, Pass]:
    """{checker id: Pass}, insertion-ordered (registration order)."""
    return dict(_REGISTRY)


def default_root() -> str:
    """Repository root (three levels above this file's package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def dotted_name(node: ast.AST) -> str | None:
    """Dotted name of an expression ('time.time', 'jnp.asarray'), or
    None for anything that is not a plain attribute chain. The one
    AST helper every pass leans on."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def collect_functions(tree: ast.AST
                      ) -> tuple[dict[str, ast.AST], dict[str, int]]:
    """({qualname: FunctionDef}, {class qualname: lineno}) for a
    module — the shared collector behind every roster lookup."""
    found: dict[str, ast.AST] = {}
    classes: dict[str, int] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                found[prefix + child.name] = child
                visit(child, prefix + child.name + ".")
            elif isinstance(child, ast.ClassDef):
                classes[prefix + child.name] = child.lineno
                visit(child, prefix + child.name + ".")

    visit(tree, "")
    return found, classes


def read_rostered(root: str, rel: str, checker: str
                  ) -> tuple[str | None, Finding | None]:
    """Read one rostered file; a missing/unreadable file is a FINDING
    (the roster rotted or the root is wrong), never a traceback out
    of the gating step."""
    try:
        with open(os.path.join(root, rel), encoding="utf-8") as f:
            return f.read(), None
    except OSError as exc:
        return None, Finding(
            rel, 1, checker, "",
            f"rostered file cannot be read ({exc.strerror or exc}) — "
            "moved/renamed? update the roster")


def enclosing_class_line(classes: dict[str, int], qual: str) -> int:
    """Line of the deepest class prefix of `qual` that exists in
    `classes` ({"A.B": lineno}); 1 when even the class is gone. The
    shared anchor rule for "registered function not found" findings —
    the report lands where the rename happened, not at line 1."""
    parts = qual.split(".")
    for cut in range(len(parts) - 1, 0, -1):
        prefix = ".".join(parts[:cut])
        if prefix in classes:
            return classes[prefix]
    return 1


# -- inline suppressions ----------------------------------------------------

# `# analysis: allow[<checker>] <mandatory reason>`; several pragmas
# may share a line (finditer). The id charset matches registered ids.
_PRAGMA_RE = re.compile(
    r"#\s*analysis:\s*allow\[([A-Za-z0-9_-]+)\]([^#\n]*)")


@dataclasses.dataclass(frozen=True)
class Pragma:
    """One suppression-pragma occurrence."""

    path: str
    line: int                 # where the pragma itself sits
    checker: str
    reason: str
    covers: tuple             # finding lines it suppresses


def _statement_extents(source: str) -> list[tuple[int, int]]:
    """``[(lineno, end_lineno)]`` for every multi-line SIMPLE
    statement (no nested block) — the lexical extents pragma coverage
    expands over. A multi-line call or assignment reports findings at
    sub-expression lines, so a pragma anchored on (or inside) the
    statement must cover every line of it. ``[]`` when the file does
    not parse: coverage then stays line-anchored."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    out: list[tuple[int, int]] = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.stmt)
                and "body" not in node._fields
                and "cases" not in node._fields
                and (node.end_lineno or node.lineno) > node.lineno):
            out.append((node.lineno, node.end_lineno))
    return out


def collect_pragmas(path: str, source: str
                    ) -> tuple[list, list]:
    """Scan one file for suppression pragmas.

    Returns ``([Pragma, ...], reasonless_findings)``. A pragma covers
    the FULL lexical extent of the statement it sits on (or inside —
    a comment line between the continuation lines of a multi-line
    call counts), because findings anchor at sub-expression lines,
    not at the statement's first line. A pragma on a comment-only
    line also covers the statement it annotates (the next non-blank,
    non-comment line, again to its full extent); a pragma with no
    reason text is a ``pragma`` finding and suppresses nothing."""
    pragmas: list[Pragma] = []
    bad: list[Finding] = []
    lines = source.splitlines()
    extents: list[tuple[int, int]] | None = None  # computed lazily
    for lineno, text in enumerate(lines, start=1):
        for m in _PRAGMA_RE.finditer(text):
            checker, reason = m.group(1), m.group(2).strip()
            if not reason:
                bad.append(Finding(
                    path, lineno, PRAGMA_CHECKER, checker,
                    f"suppression pragma allow[{checker}] without a "
                    "reason — every exception must say why"))
                continue
            covers = {lineno}
            if text.lstrip().startswith("#"):
                # comment-only pragma: also covers the statement it
                # annotates — the next non-blank, non-comment line
                for j in range(lineno, len(lines)):
                    nxt = lines[j].strip()
                    if nxt and not nxt.startswith("#"):
                        covers.add(j + 1)
                        break
            if extents is None:
                extents = _statement_extents(source)
            for anchor in sorted(covers):
                # innermost simple statement containing the anchor:
                # cover its whole lexical extent
                span: tuple[int, int] | None = None
                for s, e in extents:
                    if s <= anchor <= e and (
                            span is None
                            or e - s < span[1] - span[0]):
                        span = (s, e)
                if span is not None:
                    covers.update(range(span[0], span[1] + 1))
            pragmas.append(Pragma(path, lineno, checker, reason,
                                  tuple(sorted(covers))))
    return pragmas, bad


def pragma_lines(pragmas: Iterable) -> dict[int, dict[str, str]]:
    """{covered line: {checker id: reason}} from Pragma occurrences —
    the lookup shape ``apply_pragmas`` consumes."""
    by_line: dict[int, dict[str, str]] = {}
    for p in pragmas:
        for ln in p.covers:
            by_line.setdefault(ln, {})[p.checker] = p.reason
    return by_line


def apply_pragmas(pragmas: dict[int, dict[str, str]],
                  findings: Iterable[Finding]
                  ) -> tuple[list[Finding], list[tuple[Finding, str]]]:
    """Split one file's findings into (kept, [(suppressed, reason)])."""
    kept: list[Finding] = []
    suppressed: list[tuple[Finding, str]] = []
    for f in findings:
        reason = pragmas.get(f.line, {}).get(f.checker)
        if reason is None:
            kept.append(f)
        else:
            suppressed.append((f, reason))
    return kept, suppressed


# -- the driver -------------------------------------------------------------

@dataclasses.dataclass
class Report:
    """Outcome of one ``run_analysis`` invocation."""

    root: str
    checkers: tuple[str, ...]
    findings: list            # unsuppressed Findings (the gate fails on any)
    suppressed: list          # [(Finding, reason)]

    @property
    def ok(self) -> bool:
        return not self.findings


def run_analysis(root: str | None = None,
                 checkers: Iterable[str] | None = None) -> Report:
    """Run the selected passes (default: all registered), apply inline
    suppressions over every rostered file, and fold reason-less
    pragmas in as findings of the ``pragma`` checker."""
    root = root if root is not None else default_root()
    registry = registered_passes()
    if checkers is None:
        selected = list(registry.values())
    else:
        selected = []
        for cid in checkers:
            if cid not in registry:
                raise KeyError(
                    f"unknown checker {cid!r}; registered: "
                    f"{sorted(registry)}")
            selected.append(registry[cid])

    raw: list[Finding] = []
    files: set[str] = set()
    for p in selected:
        raw.extend(p.run(root))
        files.update(p.roster(root))

    ran = {p.id for p in selected}
    findings: list[Finding] = []
    suppressed: list[tuple[Finding, str]] = []
    by_file: dict[str, list[Finding]] = {}
    for f in raw:
        by_file.setdefault(f.path, []).append(f)
    for rel in sorted(files | set(by_file)):
        full = os.path.join(root, rel)
        try:
            with open(full, encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            # a finding about a missing file still surfaces; there is
            # just nothing to scan for pragmas
            findings.extend(by_file.get(rel, []))
            continue
        pragmas, bad = collect_pragmas(rel, source)
        kept, supp = apply_pragmas(pragma_lines(pragmas),
                                   by_file.get(rel, []))
        findings.extend(kept)
        findings.extend(bad)     # reason-less pragmas: unsuppressable
        suppressed.extend(supp)
        # stale-suppression rot: a pragma whose checker RAN but that
        # matched no finding is dead weight that would silently
        # swallow the next genuine finding landing on its line
        hit = {(f.line, f.checker) for f, _ in supp}
        for p in pragmas:
            if p.checker in ran and not any(
                    (ln, p.checker) in hit for ln in p.covers):
                findings.append(Finding(
                    rel, p.line, PRAGMA_CHECKER, p.checker,
                    f"suppression pragma allow[{p.checker}] matched "
                    "no finding — stale; remove it"))
            elif p.checker not in registry:
                findings.append(Finding(
                    rel, p.line, PRAGMA_CHECKER, p.checker,
                    f"suppression pragma names unknown checker "
                    f"{p.checker!r}; registered: {sorted(registry)}"))

    findings.sort(key=lambda f: (f.path, f.line, f.checker, f.message))
    suppressed.sort(key=lambda fr: (fr[0].path, fr[0].line))
    return Report(root=root, checkers=tuple(p.id for p in selected),
                  findings=findings, suppressed=suppressed)


# -- reporters --------------------------------------------------------------

def render_text(report: Report) -> str:
    """Human reporter: one finding per line plus a summary tail."""
    lines = [str(f) for f in report.findings]
    lines.append(
        f"[analysis] checkers: {', '.join(report.checkers)} — "
        f"{len(report.findings)} finding(s), "
        f"{len(report.suppressed)} suppressed")
    return "\n".join(lines)


def report_json(report: Report) -> dict:
    """Machine reporter (the ``--json`` CLI shape). STABLE: external
    tooling consumes this — tests/test_analysis.py pins the keys."""
    return {
        "version": 1,
        "root": report.root,
        "checkers": list(report.checkers),
        "counts": {"findings": len(report.findings),
                   "suppressed": len(report.suppressed)},
        "findings": [f.to_dict() for f in report.findings],
        "suppressed": [{**f.to_dict(), "reason": reason}
                       for f, reason in report.suppressed],
    }


def report_sarif(report: Report) -> dict:
    """SARIF 2.1.0 reporter (the ``--sarif`` CLI shape) — the format
    CI renders as inline code annotations. One run, one rule per
    checker that RAN (so annotation UIs can group by rule even on a
    clean report), one result per unsuppressed finding; suppressed
    findings are omitted (they are the accepted exceptions, not
    annotations to re-litigate on every PR)."""
    rules = [{"id": cid} for cid in report.checkers]
    rule_ids = {cid for cid in report.checkers}
    for f in report.findings:
        if f.checker not in rule_ids:      # e.g. the implicit `pragma`
            rule_ids.add(f.checker)
            rules.append({"id": f.checker})
    results = []
    for f in report.findings:
        message = (f"[{f.symbol}] {f.message}" if f.symbol
                   else f.message)
        results.append({
            "ruleId": f.checker,
            "level": "error",
            "message": {"text": message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line},
                },
            }],
        })
    return {
        "$schema": ("https://json.schemastore.org/sarif-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "cloud_server_tpu.analysis",
                "rules": rules,
            }},
            "results": results,
        }],
    }

"""CLI: ``python -m cloud_server_tpu.analysis [--json | --sarif]
[--checker <id>]... [repo_root]``.

Exit status 0 = every pass is clean (suppressions honored); 1 = at
least one unsuppressed finding; 2 = bad usage (unknown checker id, or
``--json`` combined with ``--sarif``). Text findings go to stderr
(``path:line: [checker] [symbol] message``); ``--json`` writes the
stable machine shape to stdout instead, ``--sarif`` the SARIF 2.1.0
shape CI renders as code annotations.
"""

import argparse
import json
import sys

from cloud_server_tpu.analysis import (registered_passes, render_text,
                                       report_json, run_analysis)
from cloud_server_tpu.analysis.framework import report_sarif


def main(argv: list[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m cloud_server_tpu.analysis",
        description="Serving-stack static analysis suite.")
    parser.add_argument("root", nargs="?", default=None,
                        help="repository root (default: autodetected)")
    fmt = parser.add_mutually_exclusive_group()
    fmt.add_argument("--json", action="store_true",
                     help="emit the stable JSON report on stdout")
    fmt.add_argument("--sarif", action="store_true",
                     help="emit a SARIF 2.1.0 report on stdout "
                          "(for CI code annotations)")
    parser.add_argument("--checker", action="append", default=None,
                        metavar="ID",
                        help="run only this checker (repeatable); "
                             f"ids: {sorted(registered_passes())}")
    args = parser.parse_args(argv[1:])
    try:
        report = run_analysis(args.root, checkers=args.checker)
    except KeyError as exc:
        print(f"[analysis] {exc.args[0]}", file=sys.stderr)
        return 2
    if args.json:
        json.dump(report_json(report), sys.stdout, indent=2)
        print()
    elif args.sarif:
        json.dump(report_sarif(report), sys.stdout, indent=2)
        print()
    else:
        print(render_text(report), file=sys.stderr)
    return 0 if report.ok else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))

"""CLI: ``python -m cloud_server_tpu.analysis [repo_root]``.

Exit status 0 = every registered hot-path function is clean; 1 = at
least one finding (each printed as ``path:line: [symbol] message``).
"""

import sys

from cloud_server_tpu.analysis.hot_path import check_hot_paths


def main(argv: list[str]) -> int:
    root = argv[1] if len(argv) > 1 else None
    findings = check_hot_paths(root)
    for f in findings:
        print(f, file=sys.stderr)
    if findings:
        print(f"[analysis] {len(findings)} hot-path finding(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv))

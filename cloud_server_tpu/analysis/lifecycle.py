"""Lifecycle-discipline pass (checker id: ``lifecycle-discipline``).

PRs 13/15/17/18 multiplied the ways a request can terminate —
failover retry, live migration, prefill/decode handoff, drain
evacuation, deadline expiry, anomaly-window cancellation — and every
one of those paths must honor the same three contracts, previously
enforced only by prose comments and the tests that happen to drive
them:

  * a request that turns terminal is COMPLETED exactly once (waiters
    unblock, telemetry observes the finish, the fail handler gets its
    one offer);
  * the terminal steps inside ``_complete`` run in the documented
    order (telemetry -> fail-handler offer -> ``_done`` -> callback);
  * every KV page the scheduler allocates is released, registered
    into a slot's table, or explicitly ownership-transferred — on
    every outgoing edge, including the exception edges.

This pass proves those statically, with a path-sensitive
intraprocedural walk (returns, raises, try/except/finally, early
exits, loops to fixpoint) plus the same class-local call-graph
propagation the lock pass uses (a call to ``_finish`` is a call to
``_complete``, transitively).

Rules:

  * ``LC1 finish-exactly-once`` — every path from a terminal
    ``<req>.finish_reason = ...`` assignment to function exit must
    reach ``_complete`` (or a method that transitively calls it, or —
    inside a ``COMPLETION_OWNER_FUNCS`` function — the direct
    ``_done.set()`` it is sanctioned to perform) EXACTLY once for
    that request. Completing twice without an intervening rebind
    flags too. ``_done.set()`` calls and ``_on_done`` reads anywhere
    OUTSIDE ``_complete`` / the owner roster are findings: the PR 13
    contract ("_done stays unset when the fail handler takes over")
    only holds if ``_complete`` is the single place that fires them.
  * ``LC2 terminal ordering`` — within each rostered ``_complete``
    body: the ``observe_finish`` telemetry call precedes the
    ``_fail_handler`` offer precedes ``_done.set()`` precedes the
    ``_on_done`` callback read, verified structurally (first
    occurrence of each marker, strictly increasing lines).
  * ``LC3 page-ownership balance`` — a name bound to a
    ``BlockAllocator.alloc`` / ``import_chain`` result must, on every
    path to exit, be discharged: released (an argument to
    ``.release``), registered (extended into a ``.pages`` chain,
    stored into object state, passed as a ``pages=`` keyword), handed
    to an audited ``OWNERSHIP_TRANSFER_FUNCS`` callable, or returned
    to the caller. A ``return``/``raise``/fall-through while the name
    still owns pages is a leak; so is rebinding the name while live,
    or discarding the result expression outright. ``if x is None`` /
    truthiness tests refine the path (the None branch owns nothing).
  * ``LC4 torn-write exception-safety`` — inside a lock-held region
    (lexical ``with self._lock:`` plus the must-held propagation),
    two writes to guarded attributes (guard sets imported from the
    lock pass — ``locks.guarded_attributes``) must not bracket a
    may-raise call (device syncs, host->device staging, fault-
    injection ``check`` sites, ``open``, or an explicit ``raise``)
    unless the region is protected by ``try/finally``: an exception
    between the writes leaves the guarded state torn for the next
    lock holder.

Audited rosters (the ``SANCTIONED_SYNCS`` idiom — each entry is
checked for existence and for still doing the thing it is sanctioned
to do, so the roster can never rot into silently waving through new
code):

  * ``COMPLETION_OWNER_FUNCS`` — the router's failover/migration/
    handoff/mirror paths complete the ORIGINAL handle directly with
    ``_done.set()``: ownership of that handle transferred to the
    router when the replica's ``_complete`` offered it to the fail
    handler (True return = the router owns completion) or when
    ``migrate_export`` evacuated it. Each rostered function must
    still contain a ``_done.set()``.
  * ``TERMINAL_MARKER_FUNCS`` — ``emit_token`` assigns the terminal
    reason but its CALLER owns completion (the commit path calls
    ``_finish`` the moment the emit returns done). Each rostered
    function must still assign ``finish_reason``.
  * ``COMPLETE_FUNCS`` — the ``_complete`` bodies whose LC2
    structure is pinned; a rename breaks the roster loudly.
  * ``OWNERSHIP_TRANSFER_FUNCS`` — callables that accept ownership
    of a page list (today: the ``_Slot`` record, whose pages are
    released later through ``_release_slot``).

Known limits (deliberate, documented): the walk is intraprocedural
and name-based — appending a terminal request to a container (the
deferred-completion idiom: ``doomed.append(req)`` completed after the
lock drops) or rebinding the name discharges the per-name obligation;
the drain site is audited on its own. Exception edges are modeled at
explicit ``raise`` statements (LC4 covers the may-raise-call case);
``except`` handlers conservatively join the state from every point of
their ``try`` body. Everything here is stdlib-only (ast) and never
imports the serving stack.
"""

from __future__ import annotations

import ast

from cloud_server_tpu.analysis.framework import (Finding, Pass,
                                                 collect_functions,
                                                 default_root,
                                                 dotted_name,
                                                 enclosing_class_line,
                                                 read_rostered,
                                                 register_pass)
from cloud_server_tpu.analysis.locks import guarded_attributes

CHECKER = "lifecycle-discipline"

# The request-lifecycle modules this pass audits: both servers (the
# terminal paths), the allocator (the page side of the ledger), the
# migration snapshot layer, and the router (the completion-ownership
# transfer paths).
LIFECYCLE_ROSTER: tuple[str, ...] = (
    "cloud_server_tpu/inference/paged_server.py",
    "cloud_server_tpu/inference/server.py",
    "cloud_server_tpu/inference/block_allocator.py",
    "cloud_server_tpu/inference/migration.py",
    "cloud_server_tpu/inference/router.py",
)

# Functions sanctioned to call `_done.set()` (and complete a handle)
# OUTSIDE `_complete`: the router's failover paths own the ORIGINAL
# handle — its replica `_complete` already ran its telemetry and
# offered the fail handler (True = the router owns completion), or a
# migrate_export evacuated it without completing. Rot rule: each must
# still contain a `_done.set()` call.
COMPLETION_OWNER_FUNCS: dict[str, tuple[str, ...]] = {
    "cloud_server_tpu/inference/router.py": (
        "ReplicatedRouter._retry_submit",
        "ReplicatedRouter._migrate_submit",
        "ReplicatedRouter._handoff_one",
        "ReplicatedRouter._mirror_retry",
    ),
}

# Functions sanctioned to ASSIGN a terminal finish_reason without
# completing: their caller owns completion (the commit path calls
# `_finish` the moment the emit returns done). Rot rule: each must
# still assign `finish_reason`.
TERMINAL_MARKER_FUNCS: dict[str, tuple[str, ...]] = {
    "cloud_server_tpu/inference/server.py": ("emit_token",),
}

# The `_complete` implementations whose LC2 terminal ordering is
# pinned structurally. Rot rule: each must exist.
COMPLETE_FUNCS: dict[str, tuple[str, ...]] = {
    "cloud_server_tpu/inference/paged_server.py": (
        "PagedInferenceServer._complete",),
    "cloud_server_tpu/inference/server.py": (
        "InferenceServer._complete",),
}

# Callables that take OWNERSHIP of a page list passed to them (LC3
# "transferred"): today the `_Slot` record — its pages are released
# later through `_release_slot`, the one teardown path. Rot rule:
# each must exist (function or class) in its file.
OWNERSHIP_TRANSFER_FUNCS: dict[str, tuple[str, ...]] = {
    "cloud_server_tpu/inference/paged_server.py": ("_Slot",),
}

# allocator entry points whose results carry page ownership
_ALLOC_LEAVES = {"alloc", "import_chain"}
# container ops that register pages into an owned chain (receiver
# must be a `.pages` chain: `slot.pages.extend(fresh)`)
_REGISTER_OPS = {"extend", "append", "appendleft", "insert", "add",
                 "update"}
# container ops that stash a request for deferred completion
_ESCAPE_OPS = {"append", "appendleft", "add", "insert", "put"}
# LC4 may-raise call leaves: device syncs and host<->device staging
# (the historical torn-state causes), plus the fault-injection raise
# points and host I/O handles. `asarray`/`device_put` count only on a
# jax receiver — `np.asarray` is pure host work and cannot OOM the
# device.
_RISKY_LEAVES = {"device_get", "block_until_ready", "item"}
_RISKY_JAX_LEAVES = {"asarray", "device_put"}
_JAX_RECEIVERS = {"jax", "jnp", "jax.numpy"}
_RISKY_NAMES = {"open"}

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


# -- small AST helpers ------------------------------------------------------

def _chains_in(node: ast.AST) -> set[str]:
    """Every maximal dotted attribute chain in a subtree ('slot.req',
    'self.allocator', ...). Chains broken by calls/subscripts yield
    their inner pure chains."""
    out: set[str] = set()

    def visit(n: ast.AST) -> None:
        if isinstance(n, (ast.Attribute, ast.Name)):
            c = dotted_name(n)
            if c is not None:
                out.add(c)
                return
        for child in ast.iter_child_nodes(n):
            visit(child)

    visit(node)
    return out


def _match(var: str, chain: str) -> bool:
    """Does an occurrence of `chain` refer to (part of) `var`?
    Passing `slot` escapes `slot.req`; passing `slot.req` matches it
    exactly; touching `slot.req.tokens` touches `slot.req`."""
    return (chain == var or chain.startswith(var + ".")
            or var.startswith(chain + "."))


def _kill(env: dict, name: str) -> dict:
    """Rebinding `name` drops every tracked var rooted at it."""
    return {v: s for v, s in env.items()
            if not (v == name or v.startswith(name + "."))}


def _target_names(target: ast.AST) -> list[str]:
    """Plain names bound by an assignment/for/with target."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for elt in target.elts:
            out.extend(_target_names(elt))
        return out
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    return []


def _merge(*envs):
    """Union-per-var merge of abstract environments; None (an
    unreachable path) is the identity."""
    live = [e for e in envs if e is not None]
    if not live:
        return None
    out: dict[str, frozenset] = {}
    for e in live:
        for v, states in e.items():
            out[v] = out.get(v, frozenset()) | states
    return out


def _is_alloc_call(node: ast.AST) -> str | None:
    """'alloc' / 'import_chain' when `node` is a page-owning
    allocator call (`self.allocator.alloc(...)`), else None."""
    if not (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _ALLOC_LEAVES):
        return None
    recv = dotted_name(node.func.value) or ""
    leaf = recv.split(".")[-1].lower()
    return node.func.attr if "alloc" in leaf else None


def _done_set_base(node: ast.AST) -> str | None:
    """'req' for a `req._done.set()` call node, else None."""
    if (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "set"):
        chain = dotted_name(node.func.value)
        if chain is not None and chain.endswith("._done"):
            return chain[:-len("._done")]
    return None


# -- the path-sensitive walker ----------------------------------------------

class _Flow:
    """Abstract interpretation over one function body: statements in
    order, both branches of every `if` (with optional refinement),
    loops to fixpoint, try/except joining the handler from every
    body point, `finally` applied to early exits. Subclasses define
    the per-statement transfer and the exit obligation."""

    MAX_LOOP_PASSES = 8

    def __init__(self, path: str, qual: str):
        self.path = path
        self.qual = qual
        self.findings: dict[tuple, Finding] = {}
        self._finally_stack: list[list] = []
        self._loops: list[dict] = []

    # subclass hooks ---------------------------------------------------------

    def stmt(self, node: ast.stmt, env: dict) -> dict:
        return env

    def expr(self, node: ast.AST | None, env: dict) -> dict:
        return env

    def refine(self, test: ast.AST, branch: bool,
               env: dict) -> dict | None:
        if isinstance(test, ast.Constant):
            return env if bool(test.value) == branch else None
        return env

    def on_return(self, node: ast.Return, env: dict) -> dict:
        return env

    def on_exit(self, env: dict, line: int, kind: str) -> None:
        pass

    # driver -----------------------------------------------------------------

    def run(self, fn: ast.AST) -> list[Finding]:
        env = self.walk(fn.body, {})
        if env is not None:
            last = fn.body[-1]
            self.on_exit(env, getattr(last, "end_lineno", None)
                         or last.lineno, "falls off the end")
        return list(self.findings.values())

    def _apply_finallys(self, env: dict) -> dict:
        saved = self._finally_stack
        try:
            for i in range(len(saved) - 1, -1, -1):
                self._finally_stack = saved[:i]
                out = self.walk(list(saved[i]), env)
                if out is not None:
                    env = out
        finally:
            self._finally_stack = saved
        return env

    def walk(self, stmts: list, env: dict | None) -> dict | None:
        for s in stmts:
            if env is None:
                return None
            env = self._walk_stmt(s, env)
        return env

    def _walk_stmt(self, s: ast.stmt, env: dict) -> dict | None:
        if isinstance(s, ast.If):
            env = self.expr(s.test, env)
            t = self.walk(s.body, self.refine(s.test, True, env))
            f = self.walk(s.orelse, self.refine(s.test, False, env)) \
                if s.orelse else self.refine(s.test, False, env)
            return _merge(t, f)
        if isinstance(s, (ast.While, ast.For, ast.AsyncFor)):
            return self._walk_loop(s, env)
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                env = self.expr(item.context_expr, env)
                if item.optional_vars is not None:
                    for n in _target_names(item.optional_vars):
                        env = _kill(env, n)
            return self.walk(s.body, env)
        if isinstance(s, ast.Try):
            return self._walk_try(s, env)
        if isinstance(s, ast.Return):
            env = self.expr(s.value, env)
            env = self.on_return(s, env)
            self.on_exit(self._apply_finallys(env), s.lineno, "return")
            return None
        if isinstance(s, ast.Raise):
            env = self.expr(s.exc, env)
            self.on_exit(self._apply_finallys(env), s.lineno, "raise")
            return None
        if isinstance(s, ast.Break):
            if self._loops:
                self._loops[-1]["breaks"].append(env)
            return None
        if isinstance(s, ast.Continue):
            if self._loops:
                self._loops[-1]["continues"].append(env)
            return None
        if isinstance(s, _FUNC_NODES + (ast.ClassDef,)):
            return _kill(env, s.name)  # nested defs: not walked
        return self.stmt(s, env)

    def _walk_loop(self, s, env: dict) -> dict | None:
        ctx = {"breaks": [], "continues": []}
        self._loops.append(ctx)
        try:
            seed = env
            for _ in range(self.MAX_LOOP_PASSES):
                ctx["continues"] = []
                body_env = seed
                if isinstance(s, ast.While):
                    body_env = self.refine(
                        s.test, True, self.expr(s.test, body_env))
                else:
                    body_env = self.expr(s.iter, body_env)
                    if body_env is not None:
                        for n in _target_names(s.target):
                            body_env = _kill(body_env, n)
                after = self.walk(s.body, body_env) \
                    if body_env is not None else None
                back = _merge(after, *ctx["continues"])
                new_seed = _merge(seed, back)
                if new_seed == seed:
                    break
                seed = new_seed
            if isinstance(s, ast.While):
                out = self.refine(s.test, False,
                                  self.expr(s.test, seed))
            else:
                out = seed
            out = _merge(out, *ctx["breaks"])
        finally:
            self._loops.pop()
        if s.orelse:
            out = self.walk(s.orelse, out)
        return out

    def _walk_try(self, s: ast.Try, env: dict) -> dict | None:
        has_finally = bool(s.finalbody)
        if has_finally:
            self._finally_stack.append(s.finalbody)
        try:
            running = env  # join of every in-body point: what a
            #                handler may observe
            body = env
            for sub in s.body:
                if body is None:
                    break
                body = self._walk_stmt(sub, body)
                running = _merge(running, body)
            if s.orelse and body is not None:
                body = self.walk(s.orelse, body)
            handler_outs = []
            for h in s.handlers:
                henv = running
                if henv is not None and h.name:
                    henv = _kill(henv, h.name)
                handler_outs.append(self.walk(list(h.body), henv)
                                    if henv is not None else None)
            out = _merge(body, *handler_outs)
        finally:
            if has_finally:
                self._finally_stack.pop()
        if s.finalbody and out is not None:
            out = self.walk(s.finalbody, out)
        return out

    def report(self, key: tuple, finding: Finding) -> None:
        self.findings.setdefault(key, finding)


# -- LC1: finish-exactly-once -----------------------------------------------

_ASSIGNED, _DONE, _LIVE = "assigned", "done", "live"


class _FinishFlow(_Flow):
    """LC1 per-function walk: after `<base>.finish_reason = <terminal>`
    every path must complete `<base>` exactly once."""

    def __init__(self, path: str, qual: str, completing: set,
                 is_owner: bool):
        super().__init__(path, qual)
        self.completing = completing  # self-methods reaching _complete
        self.is_owner = is_owner      # _done.set() counts as complete

    # -- events --------------------------------------------------------------

    def _complete_event(self, env: dict, var: str, line: int) -> dict:
        states = env.get(var)
        if not states:
            return env
        new: set = set()
        for tag, aline in states:
            if tag == _ASSIGNED:
                new.add((_DONE, aline))
            elif tag == _DONE:
                self.report(
                    ("LC1-double", var, line), Finding(
                        self.path, line, CHECKER, self.qual,
                        f"{var} is completed again here — it already "
                        f"completed after its terminal finish_reason "
                        f"assignment at line {aline}; finish-exactly-"
                        "once (LC1)"))
                new.add((tag, aline))
            else:
                new.add((tag, aline))
        return {**env, var: frozenset(new)}

    def expr(self, node: ast.AST | None, env: dict) -> dict:
        if node is None:
            return env
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            args_chains: set[str] = set()
            for a in list(call.args) + [kw.value for kw in
                                        call.keywords]:
                args_chains |= _chains_in(a)
            fchain = dotted_name(call.func)
            leaf = (call.func.attr
                    if isinstance(call.func, ast.Attribute) else None)
            # a call to a completing method with the tracked handle
            # among its arguments completes the handle
            if (fchain is not None and fchain.startswith("self.")
                    and fchain[len("self."):] in self.completing):
                for var in list(env):
                    if any(chain == var for chain in args_chains):
                        env = self._complete_event(env, var,
                                                   call.lineno)
            # sanctioned owner: direct `<base>._done.set()`
            base = _done_set_base(call)
            if base is not None and self.is_owner and base in env:
                env = self._complete_event(env, base, call.lineno)
            # deferred completion: the handle escapes into a
            # container (`doomed.append(req)`) — the drain site owns
            # the obligation from here
            if leaf in _ESCAPE_OPS:
                for var in list(env):
                    if any(_match(var, c) for c in args_chains):
                        env = _kill(env, var.split(".")[0])
        return env

    def stmt(self, node: ast.stmt, env: dict) -> dict:
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = node.value
            env = self.expr(value, env)
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            rhs_chains = _chains_in(value) if value is not None \
                else set()
            for tgt in targets:
                if (isinstance(tgt, ast.Attribute)
                        and tgt.attr == "finish_reason"):
                    base = dotted_name(tgt.value)
                    if (base is not None and base != "self"
                            and not (isinstance(value, ast.Constant)
                                     and value.value is None)):
                        env = {**env,
                               base: frozenset({(_ASSIGNED,
                                                 node.lineno)})}
                    continue
                if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    # storing the handle into object state: deferred
                    # completion, tracked at the drain site
                    for var in list(env):
                        if any(_match(var, c) for c in rhs_chains):
                            env = _kill(env, var.split(".")[0])
                    continue
                for n in _target_names(tgt):
                    env = _kill(env, n)
            return env
        if isinstance(node, ast.Expr):
            return self.expr(node.value, env)
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                for n in _target_names(tgt):
                    env = _kill(env, n)
            return env
        if isinstance(node, ast.Assert):
            return self.expr(node.test, env)
        return env

    def on_exit(self, env: dict, line: int, kind: str) -> None:
        for var, states in env.items():
            for tag, aline in states:
                if tag == _ASSIGNED:
                    self.report(("LC1-leak", var, aline), Finding(
                        self.path, aline, CHECKER, self.qual,
                        f"terminal finish_reason assigned to {var} "
                        f"here, but the path that exits ({kind}, "
                        f"line {line}) never reaches _complete — "
                        "finish-exactly-once (LC1)"))


# -- LC3: page-ownership balance --------------------------------------------

class _PagesFlow(_Flow):
    """LC3 per-function walk: a name bound to an alloc/import_chain
    result must be discharged on every path to exit."""

    def __init__(self, path: str, qual: str,
                 transfer_leaves: set[str]):
        super().__init__(path, qual)
        self.transfer_leaves = transfer_leaves

    def _discharge(self, env: dict, chains: set[str]) -> dict:
        for var in list(env):
            if any(_match(var, c) for c in chains):
                env = _kill(env, var)
        return env

    def expr(self, node: ast.AST | None, env: dict) -> dict:
        if node is None or not env:
            return self._scan_drops(node, env)
        for call in ast.walk(node) if node is not None else ():
            if not isinstance(call, ast.Call):
                continue
            leaf = (call.func.attr
                    if isinstance(call.func, ast.Attribute)
                    else call.func.id
                    if isinstance(call.func, ast.Name) else None)
            arg_chains: set[str] = set()
            for a in call.args:
                arg_chains |= _chains_in(a)
            kw_chains: set[str] = set()
            pages_kw_chains: set[str] = set()
            for kw in call.keywords:
                c = _chains_in(kw.value)
                kw_chains |= c
                if kw.arg == "pages":
                    pages_kw_chains |= c
            recv = (dotted_name(call.func.value)
                    if isinstance(call.func, ast.Attribute) else None)
            if leaf == "release":
                env = self._discharge(env, arg_chains | kw_chains)
            elif (leaf in _REGISTER_OPS and recv is not None
                    and (recv == "pages"
                         or recv.endswith(".pages"))):
                env = self._discharge(env, arg_chains)
            elif leaf in self.transfer_leaves:
                env = self._discharge(env, arg_chains | kw_chains)
            if pages_kw_chains:
                env = self._discharge(env, pages_kw_chains)
        return env

    def _scan_drops(self, node: ast.AST | None, env: dict) -> dict:
        return env

    @staticmethod
    def _alias_chains(value: ast.AST) -> set[str] | None:
        """Chains in an alias-shaped RHS (`y`, `a.b`, `a + b`,
        `[*a, *b]`) — the shapes through which page OWNERSHIP moves
        into the assignment target. A call that merely reads the
        name (`np.asarray([i for i in fill])`) is not a move; the
        source keeps its obligation."""
        if isinstance(value, (ast.Name, ast.Attribute)):
            c = dotted_name(value)
            return {c} if c is not None else None
        if isinstance(value, ast.BinOp) and isinstance(value.op,
                                                       ast.Add):
            left = _PagesFlow._alias_chains(value.left)
            right = _PagesFlow._alias_chains(value.right)
            if left is not None or right is not None:
                return (left or set()) | (right or set())
            return None
        if isinstance(value, (ast.List, ast.Tuple)):
            out: set[str] = set()
            for elt in value.elts:
                sub = _PagesFlow._alias_chains(
                    elt.value if isinstance(elt, ast.Starred)
                    else elt)
                if sub:
                    out |= sub
            return out or None
        return None

    def stmt(self, node: ast.stmt, env: dict) -> dict:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = node.value
            if value is None:
                return env
            env = self.expr(value, env)
            kind = next((k for k in (_is_alloc_call(c)
                                     for c in ast.walk(value))
                         if k is not None), None)
            rhs_chains = _chains_in(value)
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in targets):
                # registered into object state (tables row, slot
                # field): discharged
                env = self._discharge(env, rhs_chains)
            moved: frozenset | None = None
            alias = self._alias_chains(value)
            if alias:
                for var in list(env):
                    if any(_match(var, c) for c in alias):
                        # ownership moves into the target
                        moved = (moved or frozenset()) | env[var]
                        env = _kill(env, var)
            for tgt in targets:
                if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    moved = None
                    continue
                for n in _target_names(tgt):
                    states = env.get(n)
                    if states:
                        for tag, aline, akind in states:
                            self.report(
                                ("LC3-rebind", n, aline), Finding(
                                    self.path, node.lineno, CHECKER,
                                    self.qual,
                                    f"{n} is rebound here while still "
                                    f"owning the pages {akind}'d at "
                                    f"line {aline} — release, "
                                    "register, or transfer them "
                                    "first (LC3)"))
                    env = _kill(env, n)
            names = [n for tgt in targets
                     for n in _target_names(tgt)]
            if kind is not None and len(names) == 1:
                env = {**env, names[0]:
                       frozenset({(_LIVE, node.lineno, kind)})}
            elif moved and len(names) == 1:
                env = {**env, names[0]: moved}
            return env
        if isinstance(node, ast.AugAssign):
            env = self.expr(node.value, env)
            if isinstance(node.target, ast.Attribute):
                # `slot.pages += fresh`: registered
                env = self._discharge(env, _chains_in(node.value))
            return env
        if isinstance(node, ast.Expr):
            env = self.expr(node.value, env)
            for c in ast.walk(node.value):
                kind = _is_alloc_call(c)
                if kind is not None:
                    self.report(("LC3-drop", node.lineno), Finding(
                        self.path, node.lineno, CHECKER, self.qual,
                        f"result of {kind}() is discarded — the "
                        "pages it allocated can never be released "
                        "(LC3)"))
            return env
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                for n in _target_names(tgt):
                    env = _kill(env, n)
            return env
        return env

    def refine(self, test: ast.AST, branch: bool,
               env: dict) -> dict | None:
        base = super().refine(test, branch, env)
        if base is None:
            return None
        # `if fresh:` / `if fresh is None:` — the empty branch owns
        # nothing, so the obligation drops there
        name, empty_when = None, None
        if isinstance(test, ast.Name):
            name, empty_when = test.id, False
        elif (isinstance(test, ast.UnaryOp)
                and isinstance(test.op, ast.Not)
                and isinstance(test.operand, ast.Name)):
            name, empty_when = test.operand.id, True
        elif (isinstance(test, ast.Compare)
                and isinstance(test.left, ast.Name)
                and len(test.ops) == 1
                and len(test.comparators) == 1
                and isinstance(test.comparators[0], ast.Constant)
                and test.comparators[0].value is None):
            if isinstance(test.ops[0], ast.Is):
                name, empty_when = test.left.id, True
            elif isinstance(test.ops[0], ast.IsNot):
                name, empty_when = test.left.id, False
        if name is not None and name in base \
                and branch == empty_when:
            return _kill(base, name)
        return base

    def on_return(self, node: ast.Return, env: dict) -> dict:
        if node.value is not None:
            # returning the pages hands ownership to the caller
            env = self._discharge(env, _chains_in(node.value))
        return env

    def on_exit(self, env: dict, line: int, kind: str) -> None:
        for var, states in env.items():
            for tag, aline, akind in states:
                if tag == _LIVE:
                    self.report(("LC3-leak", var, aline), Finding(
                        self.path, aline, CHECKER, self.qual,
                        f"{var} owns the pages {akind}'d here, but "
                        f"the path that exits ({kind}, line {line}) "
                        "never releases, registers, or transfers "
                        "them (LC3)"))


# -- LC2: terminal ordering inside _complete --------------------------------

_LC2_ORDER = (
    ("telemetry", "the observe_finish telemetry call"),
    ("fail_handler", "the _fail_handler offer"),
    ("done_set", "_done.set()"),
    ("on_done", "the _on_done callback read"),
)


def _check_complete_body(path: str, qual: str,
                         fn: ast.AST) -> list[Finding]:
    first: dict[str, int] = {}

    def note(key: str, line: int) -> None:
        if key not in first or line < first[key]:
            first[key] = line

    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            name = dotted_name(n.func) or ""
            if name.split(".")[-1] == "observe_finish":
                note("telemetry", n.lineno)
            if _done_set_base(n) is not None:
                note("done_set", n.lineno)
        if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load):
            if n.attr == "_fail_handler":
                note("fail_handler", n.lineno)
            if n.attr == "_on_done":
                note("on_done", n.lineno)
    out: list[Finding] = []
    prev_key, prev_line = None, -1
    for key, desc in _LC2_ORDER:
        line = first.get(key)
        if line is None:
            out.append(Finding(
                path, fn.lineno, CHECKER, qual,
                f"_complete is missing {desc} — the terminal order "
                "is telemetry -> fail-handler offer -> _done.set() "
                "-> _on_done (LC2)"))
            continue
        if line < prev_line:
            out.append(Finding(
                path, line, CHECKER, qual,
                f"{desc} (line {line}) runs before "
                f"{dict(_LC2_ORDER)[prev_key]} (line {prev_line}) — "
                "the terminal order is telemetry -> fail-handler "
                "offer -> _done.set() -> _on_done (LC2)"))
        prev_key, prev_line = key, max(prev_line, line)
    return out


# -- LC4: torn writes under a lock ------------------------------------------

class _TornWriteScan:
    """Ordered walk of one method: inside a lock-held region, two
    guarded-attribute writes must not bracket a may-raise call unless
    a try/finally protects the region."""

    def __init__(self, path: str, qual: str, guards: dict,
                 base_held: frozenset, locks: set):
        self.path = path
        self.qual = qual
        self.guards = guards
        self.base_held = base_held
        self.locks = locks
        self.findings: list[Finding] = []
        # (attr, line) of the last guarded write in the current
        # held region; risky call pending since that write
        self._last_write: tuple | None = None
        self._risky: tuple | None = None

    def run(self, fn: ast.AST) -> list[Finding]:
        self._visit_body(fn.body, bool(self.base_held), 0)
        return self.findings

    def _reset(self) -> None:
        self._last_write = None
        self._risky = None

    def _write(self, attr: str, line: int, held: bool,
               protected: int) -> None:
        if not held or attr not in self.guards:
            return
        if (self._last_write is not None and self._risky is not None
                and not protected):
            w1a, w1l = self._last_write
            desc, rline = self._risky
            self.findings.append(Finding(
                self.path, rline, CHECKER, self.qual,
                f"lock-held region writes {w1a} (line {w1l}) and "
                f"{attr} (line {line}) with {desc} between them — "
                "an exception there leaves the guarded state torn; "
                "protect with try/finally (LC4)"))
        self._last_write = (attr, line)
        self._risky = None

    def _risk(self, desc: str, line: int, held: bool) -> None:
        if held and self._last_write is not None \
                and self._risky is None:
            self._risky = (desc, line)

    def _visit_body(self, stmts, held: bool, protected: int) -> None:
        for s in stmts:
            self._visit(s, held, protected)

    def _visit(self, node: ast.AST, held: bool,
               protected: int) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = held
            for item in node.items:
                attr = self._self_attr(item.context_expr)
                if attr in self.locks:
                    acquired = True
                else:
                    self._visit(item.context_expr, held, protected)
            if acquired and not held:
                self._reset()  # fresh region
            self._visit_body(node.body, acquired, protected)
            if acquired and not held:
                self._reset()  # region closed
            return
        if isinstance(node, ast.Try):
            prot = protected + (1 if node.finalbody else 0)
            self._visit_body(node.body, held, prot)
            for h in node.handlers:
                self._visit_body(h.body, held, prot)
            self._visit_body(node.orelse, held, prot)
            self._visit_body(node.finalbody, held, protected)
            return
        if isinstance(node, ast.Raise):
            self._risk("an explicit raise", node.lineno, held)
            return
        if isinstance(node, _FUNC_NODES + (ast.ClassDef,)):
            return
        if isinstance(node, ast.Call):
            self._visit_call(node, held, protected)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign,
                             ast.AnnAssign, ast.Delete)):
            # value first (its calls precede the store), then targets
            for field in ("value",):
                v = getattr(node, field, None)
                if v is not None:
                    self._visit(v, held, protected)
            targets = (node.targets if isinstance(
                node, (ast.Assign, ast.Delete))
                else [node.target])
            for tgt in targets:
                attr = self._store_attr(tgt)
                if attr is not None:
                    self._write(attr, node.lineno, held, protected)
                else:
                    self._visit(tgt, held, protected)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._visit(child, held, protected)
            elif isinstance(child, ast.AST):
                self._visit(child, held, protected)

    def _visit_call(self, node: ast.Call, held: bool,
                    protected: int) -> None:
        func = node.func
        leaf = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        recv = (dotted_name(func.value)
                if isinstance(func, ast.Attribute) else "")
        if (leaf in _RISKY_LEAVES or leaf in _RISKY_NAMES
                or (leaf in _RISKY_JAX_LEAVES
                    and recv in _JAX_RECEIVERS)):
            self._risk(f"may-raise call {dotted_name(func) or leaf}()",
                       node.lineno, held)
        elif leaf == "check" and recv and "fault" in recv.lower():
            self._risk("the fault-injection check() raise point",
                       node.lineno, held)
        # a mutator call on a guarded attribute is a write to it
        if (isinstance(func, ast.Attribute)
                and leaf in _REGISTER_OPS | {"remove", "pop",
                                             "popleft", "clear",
                                             "discard", "setdefault"}):
            attr = self._self_attr(func.value)
            if attr is not None:
                self._write(attr, node.lineno, held, protected)
        for a in node.args:
            self._visit(a, held, protected)
        for kw in node.keywords:
            self._visit(kw.value, held, protected)

    @staticmethod
    def _self_attr(node: ast.AST) -> str | None:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    def _store_attr(self, tgt: ast.AST) -> str | None:
        if isinstance(tgt, ast.Subscript):
            return self._self_attr(tgt.value)
        return self._self_attr(tgt)


# -- per-file orchestration -------------------------------------------------

def _completing_methods(cls: ast.ClassDef) -> set[str]:
    """Self-methods that reach `_complete` transitively — the
    class-local call-graph propagation the lock pass also uses."""
    methods = {c.name: c for c in cls.body
               if isinstance(c, _FUNC_NODES)}
    if "_complete" not in methods:
        return set()
    calls: dict[str, set[str]] = {}
    for name, fn in methods.items():
        out: set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                chain = dotted_name(n.func)
                if chain is not None and chain.startswith("self."):
                    leaf = chain[len("self."):]
                    if leaf in methods:
                        out.add(leaf)
        calls[name] = out
    comp = {"_complete"}
    changed = True
    while changed:
        changed = False
        for name, callees in calls.items():
            if name not in comp and callees & comp:
                comp.add(name)
                changed = True
    return comp


def _iter_functions(tree: ast.Module):
    """(qualname, class node | None, completing set, fn node) for
    every function; nested defs are visited at their own qualname."""
    def visit(node, prefix, cls, comp):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES):
                yield prefix + child.name, cls, comp, child
                yield from visit(child, prefix + child.name + ".",
                                 cls, comp)
            elif isinstance(child, ast.ClassDef):
                sub = _completing_methods(child)
                yield from visit(child, prefix + child.name + ".",
                                 child, sub)

    yield from visit(tree, "", None, set())


def check_source(path: str, source: str, *,
                 owner_funcs: tuple[str, ...] | None = None,
                 marker_funcs: tuple[str, ...] | None = None,
                 complete_funcs: tuple[str, ...] | None = None,
                 transfer_funcs: tuple[str, ...] | None = None
                 ) -> list[Finding]:
    """Run LC1–LC4 over one file. Rosters default to the audited
    module constants keyed by `path`; fixtures inject their own."""
    if owner_funcs is None:
        owner_funcs = COMPLETION_OWNER_FUNCS.get(path, ())
    if marker_funcs is None:
        marker_funcs = TERMINAL_MARKER_FUNCS.get(path, ())
    if complete_funcs is None:
        complete_funcs = COMPLETE_FUNCS.get(path, ())
    if transfer_funcs is None:
        transfer_funcs = OWNERSHIP_TRANSFER_FUNCS.get(path, ())
    tree = ast.parse(source, filename=path)
    functions, classes = collect_functions(tree)
    out: list[Finding] = []

    # roster rot: every sanctioned symbol must exist and still do the
    # thing it is sanctioned to do (the SANCTIONED_SYNCS idiom)
    def missing(qual: str, what: str) -> Finding:
        return Finding(
            path, enclosing_class_line(classes, qual), CHECKER, qual,
            f"{what} roster names {qual} but it does not exist — "
            "renamed? update the roster")

    for qual in owner_funcs:
        fn = functions.get(qual)
        if fn is None:
            out.append(missing(qual, "COMPLETION_OWNER_FUNCS"))
        elif not any(_done_set_base(n) is not None
                     for n in ast.walk(fn)):
            out.append(Finding(
                path, fn.lineno, CHECKER, qual,
                "sanction rot: COMPLETION_OWNER_FUNCS names this "
                "function but it no longer contains a _done.set() "
                "call — remove it from the roster"))
    for qual in marker_funcs:
        fn = functions.get(qual)
        if fn is None:
            out.append(missing(qual, "TERMINAL_MARKER_FUNCS"))
        elif not any(isinstance(n, ast.Attribute)
                     and n.attr == "finish_reason"
                     and isinstance(n.ctx, ast.Store)
                     for n in ast.walk(fn)):
            out.append(Finding(
                path, fn.lineno, CHECKER, qual,
                "sanction rot: TERMINAL_MARKER_FUNCS names this "
                "function but it no longer assigns finish_reason — "
                "remove it from the roster"))
    for qual in complete_funcs:
        if qual not in functions:
            out.append(missing(qual, "COMPLETE_FUNCS"))
    for qual in transfer_funcs:
        if qual not in functions and qual not in classes:
            out.append(missing(qual, "OWNERSHIP_TRANSFER_FUNCS"))

    transfer_leaves = {q.split(".")[-1] for q in transfer_funcs}
    owner_set = set(owner_funcs)
    marker_set = set(marker_funcs)

    for qual, cls, completing, fn in _iter_functions(tree):
        is_owner = qual in owner_set
        # LC1a: terminal assignment -> complete exactly once
        if qual not in marker_set \
                and fn.name not in ("_complete",):
            out.extend(_FinishFlow(path, qual, completing,
                                   is_owner).run(fn))
        # LC1b: completion primitives live only in _complete and the
        # sanctioned owner functions
        if fn.name != "_complete" and not is_owner:
            for n in ast.walk(fn):
                if isinstance(n, _FUNC_NODES) and n is not fn:
                    pass  # nested defs get their own pass
                base = _done_set_base(n) if isinstance(n, ast.Call) \
                    else None
                if base is not None:
                    out.append(Finding(
                        path, n.lineno, CHECKER, qual,
                        f"{base}._done.set() outside _complete — "
                        "only _complete (and the audited "
                        "COMPLETION_OWNER_FUNCS) may fire the done "
                        "event (LC1)"))
                if (isinstance(n, ast.Attribute)
                        and n.attr == "_on_done"
                        and isinstance(n.ctx, ast.Load)):
                    out.append(Finding(
                        path, n.lineno, CHECKER, qual,
                        "_on_done is read (to invoke) outside "
                        "_complete — only _complete (and the "
                        "audited COMPLETION_OWNER_FUNCS) may run "
                        "the completion callback (LC1)"))
        # LC2: terminal ordering, structurally
        if fn.name == "_complete":
            out.extend(_check_complete_body(path, qual, fn))
        # LC3: page-ownership balance
        out.extend(_PagesFlow(path, qual, transfer_leaves).run(fn))

    # LC4: torn guarded writes, guard sets imported from the lock pass
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        guards, must = guarded_attributes(path, node)
        if not guards:
            continue
        locks = {g for gs in guards.values() for g in gs}
        for child in node.body:
            if not isinstance(child, _FUNC_NODES):
                continue
            if child.name in ("__init__", "__post_init__", "__new__"):
                continue
            held = must.get(child.name, frozenset())
            out.extend(_TornWriteScan(
                path, f"{node.name}.{child.name}", guards,
                held, locks).run(child))
    return out


def check_lifecycle(root: str | None = None) -> list[Finding]:
    if root is None:
        root = default_root()
    out: list[Finding] = []
    for rel in LIFECYCLE_ROSTER:
        source, missing = read_rostered(root, rel, CHECKER)
        if missing is not None:
            out.append(missing)
            continue
        out.extend(check_source(rel, source))
    return out


register_pass(Pass(
    id=CHECKER,
    title="requests finish exactly once through _complete (in the "
          "documented terminal order) and every allocated page is "
          "released, registered, or ownership-transferred on every "
          "path",
    run=check_lifecycle,
    roster=lambda root: LIFECYCLE_ROSTER,
))

"""Static invariant checks for the serving stack — a multi-pass,
stdlib-only analysis framework.

``python -m cloud_server_tpu.analysis [--json] [--checker <id>]``
runs every registered pass over the serving stack and exits non-zero
on any unsuppressed finding; the same gate runs as a tier-1 test
(``tests/test_analysis.py``) and as an explicit ``run_tests.sh``
step. Checker ids, rules, and the suppression-pragma syntax are
cataloged in ``docs/analysis.md`` (drift-checked both ways).

The four passes shipped today:

  * ``hot-path`` (``hot_path.py``) — the per-iteration scheduler code
    registered in ``HOT_PATHS`` must stay free of device work,
    blocking transfers, numpy-buffer materialization, wall-clock
    reads, and host I/O.
  * ``lock-discipline`` (``locks.py``) — infers each class's
    guarded-attribute sets from its ``with self._lock:`` /
    ``with self._step_lock:`` regions and flags unlocked access to
    shared state, blocking calls while a lock is held, and
    acquisitions against the declared ``_step_lock -> _lock`` order.
  * ``dispatch-discipline`` (``dispatch.py``) — ONE sanctioned
    ``device_get`` per scheduler iteration, jax-free host-policy
    modules, and statically bounded values into jitted static
    arguments (the compile-variant invariant).
  * ``lifecycle-discipline`` (``lifecycle.py``) — every terminal
    request path reaches ``_complete`` exactly once in the documented
    telemetry -> fail-handler -> ``_done`` -> callback order, every
    allocated KV page is released/registered/transferred on every
    edge, and lock-held regions cannot tear guarded state across a
    may-raise call.

Deliberate exceptions are carried in the code as
``# analysis: allow[<checker>] <reason>`` pragmas; the reason is
mandatory (a reason-less pragma is itself a finding).

Everything here is stdlib-only (ast) and never imports jax, numpy, or
the serving stack: the gate runs inside every test process, so it
must be fast and must not spend any of the process's vm.max_map_count
budget on an XLA backend it never uses.
"""

from cloud_server_tpu.analysis.framework import (  # noqa: F401
    Finding, Pass, Report, apply_pragmas, collect_pragmas,
    register_pass, registered_passes, render_text, report_json,
    run_analysis)
# importing the pass modules registers them
from cloud_server_tpu.analysis.hot_path import (  # noqa: F401
    HOT_PATHS, check_hot_paths, check_source)
from cloud_server_tpu.analysis import (  # noqa: F401
    dispatch, lifecycle, locks)

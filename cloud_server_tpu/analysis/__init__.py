"""Static invariant checks for the serving stack's host hot path.

``python -m cloud_server_tpu.analysis`` scans the per-iteration
scheduler code registered in ``hot_path.HOT_PATHS`` and exits non-zero
on any finding; the same gate runs as a tier-1 test
(``tests/test_analysis.py``).

Everything here is stdlib-only (ast) and never imports jax, numpy, or
the serving stack: the gate runs inside every test process, so it must
be fast and must not spend any of the process's vm.max_map_count
budget on an XLA backend it never uses.

The one checker shipped today is the HOT-PATH SYNC/ALLOCATION lint
(``hot_path.py``): the schedulers are engineered around one
host<->device sync per iteration, and the QoS admission policy
(``inference/qos.py``) rides INSIDE that iteration — so the functions
listed in ``HOT_PATHS`` must stay free of device work, blocking
transfers, numpy-buffer materialization, wall-clock reads, and host
I/O. The dispatch-count regression tests sample this dynamically on
one path; the lint enforces it across every registered function.
"""

from cloud_server_tpu.analysis.hot_path import (  # noqa: F401
    Finding, HOT_PATHS, check_hot_paths, check_source)

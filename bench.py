"""Benchmark harness — runs on the real TPU chip.

Times the full jitted training step (fwd+bwd+optimizer) of a ~330M-param
dense decoder LM in bfloat16 and prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference (view-sonic/Cloud-Server @ v0) publishes no numbers
(BASELINE.md: empty working tree), so `vs_baseline` is computed against the
previous round's own result (BENCH_r01.json: 26,249.5 tok/s on this same
config) — round-over-round regression tracking rather than a constant 1.0.

Config notes (measured on TPU v5e, this repo):
  * attention_impl="flash" + remat="dots" (with the flash residuals saved
    via checkpoint_name): 312 -> ~229 ms/step vs the r1 XLA-attention path.
  * the S=2048 extra compares the pallas flash kernel against XLA dense
    attention at long sequence in a training-style fwd+bwd.
  * r2 sweep results at this config (kept for provenance, all slower or
    invalid): vocab_chunk 4k/8k ~+4%, remat="attn" ~+4%, remat="none"
    fails to compile even with flash, bf16 master params -5% but changes
    optimizer numerics. Step decomposition: fwd 62 ms, bwd ~145 ms,
    optimizer 18 ms (near bandwidth-bound: ~9 GB of f32 param/moment
    traffic).
  * r3 flash-backward sweep (all kept losing variants, see
    ops/flash_attention.py): blocks 512 + staged-dq single-recompute
    backward 236 ms, blocks 512 + two-pass 242 ms, vs 221 ms for the
    1024 single-block fused backward — the block-level causal skip's
    FLOP saving loses to dq-staging HBM traffic / second recompute at
    this size (the backward is bandwidth-bound). Defaults unchanged.
  * r3 decode-attention finding (careful differential timing,
    benchmarks/decode_attention_bench.py): XLA's dense decode attention
    runs at ~790 GB/s effective at B=8/S=1024/W=1 — essentially the HBM
    roofline — so no kernel can beat it at full-length contexts; the
    paged kernel's value is block-table indirection + length-bounded
    reads (ragged contexts) at near-roofline, not a speedup at XLA's
    best shape.
  * r4 MFU sweep (benchmarks/mfu_sweep.py, matmul_roofline.py) — all
    measured LOSERS at the unchanged 330M config, baseline 215.9 ms:
    vocab_chunk 4096/8192 -> 223.7/221.6 ms (reconfirms r2);
    scan_layers_unroll 2/4 -> 240.9/254.0 ms; remat="attn" -> 226.5 ms;
    remat="none" CRASHES the remote tpu_compile_helper (HTTP 500, exit
    1) with flash AND with xla attention — the policy most likely to
    cut the backward is environment-blocked, not flash-specific.
    Roofline context: the model's own matmul shapes sustain 193-236
    TF/s in isolation (within ~15% of wide-matmul rates on this chip),
    so the plateau is inter-matmul overhead (attention kernel, norms,
    saved-activation traffic, scheduling), not matmul geometry —
    without a profiler through the tunnel (still blocked), the
    remaining levers are hand-fused pallas (qkv+rope+write, CE) whose
    plausible wins are single-digit ms each.
  * r5 fused-CE kernel (ops/fused_ce.py, ce_impl="pallas" — now the
    bench config): the r4-nominated CE lever, built and measured.
    Decomposition first (benchmarks/step_decomposition.py): full step
    220.0 = hidden fwd+bwd 189.1 + CE ~16.5 + optimizer 14.4; the
    dense CE pays f32 d_logits matmul passes + ~4 GB of logits round
    trips. Kernel A/B at the bench config
    (benchmarks/fused_ce_bench.py): CE fwd+bwd 23.5 -> 18.7 ms, FULL
    STEP 220.5 -> 214.3 ms (-2.8%) — the first move in the ~0.377 MFU
    plateau in three rounds (-> ~0.390). Variants measured: two-kernel
    bwd (dx + dW each recomputing logits) 22.0 ms; emitted-d single
    recompute + XLA dW matmul 18.7 ms (kept); row tiles 512 19.7 ms
    (256 kept); bwd vocab tiles 640 under the default 16 MB scoped
    vmem 24.1 ms (3200 with vmem_limit_bytes=100MB kept).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp

from cloud_server_tpu.utils.bench_helpers import make_prompt_fn, pct, top_up


def _baseline_tokens_per_sec() -> tuple[str, float]:
    """(round_tag, tokens/s) of the latest BENCH_r*.json present — so
    vs_baseline is a round-over-round ratio and a regression shows up as
    < 1.0 at a glance (r3's ratio-to-r1 hid a 0.6% regression vs r2).
    The tag rides in the output so a reader can tell WHICH round the
    ratio divides by (if this round's own file has already been saved
    when bench re-runs, the ratio is vs itself ~= 1.0 and the tag says
    so). Falls back to 1:1 if no prior bench file exists."""
    import glob
    here = os.path.dirname(os.path.abspath(__file__))
    paths = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
    for path in reversed(paths):
        try:
            with open(path) as f:
                value = float(json.load(f)["parsed"]["value"])
            tag = os.path.basename(path)[len("BENCH_"):-len(".json")]
            return tag, value
        except (OSError, KeyError, ValueError, TypeError):
            continue
    return "none", 0.0


def sync_device(x) -> None:
    """Force completion through the axon tunnel: `block_until_ready`
    does NOT truly block there — only a device_get does."""
    jax.device_get(jax.tree.leaves(x)[0].ravel()[0])


def diff_time_scan_multi(make_fn, args, n1: int, n2: int, *,
                         reps: int = 2, n_meas: int = 1) -> list[float]:
    """Per-iteration seconds via the two-length differential: the
    tunnel's ~100 ms fixed dispatch+sync cost cancels in
    (t(n2) - t(n1)) / (n2 - n1). Best-of-`reps` per length; pick n2 so
    (n2 - n1) x per-iter >> the fixed cost's variance (~30 ms).

    Returns `n_meas` INDEPENDENT differential estimates from ONE pair of
    compiled fns (compilation through the remote tunnel costs tens of
    seconds — the repeats that establish run-to-run spread must not pay
    it again). r3 learned why repeats matter: a single differential
    produced 12.0 us for a read that the HBM roofline bounds at ~40 us."""
    fns = {}
    for n in (n1, n2):
        fn = jax.jit(make_fn(n))
        sync_device(fn(*args))  # compile + warm
        fns[n] = fn
    out = []
    for _ in range(n_meas):
        best = {}
        for n in (n1, n2):
            b = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                sync_device(fns[n](*args))
                b = min(b, time.perf_counter() - t0)
            best[n] = b
        out.append((best[n2] - best[n1]) / (n2 - n1))
    return out


def diff_time_scan(make_fn, args, n1: int, n2: int, reps: int = 2) -> float:
    return diff_time_scan_multi(make_fn, args, n1, n2, reps=reps)[0]


def _sync(state, metrics) -> float:
    """Force completion of everything queued: metrics loss AND a state leaf
    (the optimizer update may still be in flight after the loss is ready)."""
    loss = float(metrics["loss"])
    int(jax.device_get(state.step))
    return loss


def train_bench():
    from cloud_server_tpu.config import MeshConfig, ModelConfig, TrainConfig
    from cloud_server_tpu.parallel.mesh import make_mesh
    from cloud_server_tpu.training import init_train_state, make_train_step

    model_cfg = ModelConfig(
        vocab_size=32000, embed_dim=1024, num_layers=16, num_heads=16,
        num_kv_heads=16, head_dim=64, mlp_dim=4096, max_seq_len=1024,
        dtype="bfloat16", param_dtype="float32", remat="dots",
        attention_impl="flash", ce_impl="pallas")
    batch, seq = 8, 1024
    train_cfg = TrainConfig(batch_size=batch, seq_len=seq, warmup_steps=10,
                            total_steps=100)

    mesh = make_mesh(MeshConfig())  # single chip
    state = init_train_state(model_cfg, train_cfg, mesh, jax.random.key(0))
    step, batch_sharding = make_train_step(model_cfg, train_cfg, mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (batch, seq), 0,
                           model_cfg.vocab_size), batch_sharding)
    data = {"tokens": tokens}

    for _ in range(3):
        state, metrics = step(state, data)
    _sync(state, metrics)

    n_steps = 20
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, data)
    loss_val = _sync(state, metrics)
    dt = time.perf_counter() - t0
    if loss_val != loss_val:
        raise SystemExit("bench invalid: loss is NaN")

    tokens_per_sec = batch * seq * n_steps / dt

    # Rough MFU: 6 * non-embedding params * tokens for fwd+bwd, vs 197
    # TFLOP/s bf16 peak (TPU v5e).
    n_layer_params = model_cfg.num_layers * (
        4 * model_cfg.embed_dim * model_cfg.num_heads * model_cfg.head_dim
        + 3 * model_cfg.embed_dim * model_cfg.mlp_dim)
    n_embed = 2 * model_cfg.vocab_size * model_cfg.embed_dim
    flops_per_token = 6 * (n_layer_params + n_embed)
    mfu = flops_per_token * tokens_per_sec / 197e12

    return {
        "tokens_per_sec": tokens_per_sec,
        "step_time_ms": 1000 * dt / n_steps,
        "approx_mfu": mfu,
    }


def longseq_attention_bench():
    """Training-style fwd+bwd through a 4-layer stack at S=2048:
    pallas flash kernel vs XLA dense attention."""
    import dataclasses

    from cloud_server_tpu.config import ModelConfig
    from cloud_server_tpu.models import transformer

    base = ModelConfig(
        vocab_size=8192, embed_dim=1024, num_layers=4, num_heads=16,
        num_kv_heads=16, head_dim=64, mlp_dim=4096, max_seq_len=2048,
        dtype="bfloat16", param_dtype="float32", remat="dots")
    tokens = jax.random.randint(jax.random.key(2), (4, 2048), 0,
                                base.vocab_size)
    batch = {"tokens": tokens}

    out = {}
    for impl in ("flash", "xla"):
        cfg = dataclasses.replace(base, attention_impl=impl)
        params = transformer.init_params(cfg, jax.random.key(0))

        @jax.jit
        def grad_fn(params, batch, cfg=cfg):
            def loss(p):
                l, _ = transformer.next_token_loss(p, batch, cfg)
                return l
            return jax.grad(loss)(params)

        g = grad_fn(params, batch)
        float(jax.tree.leaves(g)[0].reshape(-1)[0].astype(jnp.float32))
        n = 10
        t0 = time.perf_counter()
        for _ in range(n):
            g = grad_fn(params, batch)
        float(jax.tree.leaves(g)[0].reshape(-1)[0].astype(jnp.float32))
        out[impl] = 1000 * (time.perf_counter() - t0) / n
    return {"s2048_fwdbwd_flash_ms": out["flash"],
            "s2048_fwdbwd_xla_ms": out["xla"],
            "s2048_flash_speedup": out["xla"] / out["flash"]}


def serving_bench():
    """Steady-state continuous-batching decode on the 330M model: 8 slots
    x 1024 context, contiguous server (XLA decode) vs PAGED server
    (ops.paged_attention kernel), bf16/int8 weights and KV, and in-server
    n-gram speculative decoding.

    Keys keep their r1/r2 names for round-over-round comparability;
    "pallas" rows now mean the PAGED server + kernel (the contiguous
    pallas decode kernel was removed in r3 — it lost to XLA everywhere).

    Honesty note on absolute numbers: every scheduler iteration pays the
    axon tunnel's ~100 ms fixed dispatch+sync round trip (measured r3 —
    see benchmarks/decode_attention_bench.py), amortised here over
    decode_chunk=32 rounds. Cross-mode RATIOS are meaningful (the fixed
    cost is identical per iteration); absolute tok/s on a local TPU host
    would be uniformly higher. Kernel-level truth lives in the
    attn8k/attn1k extras (differential timing, tunnel-free)."""
    import dataclasses

    import numpy as np  # noqa: F401 (prompt construction)

    from cloud_server_tpu.config import InferConfig, ModelConfig
    from cloud_server_tpu.inference.paged_server import PagedInferenceServer
    from cloud_server_tpu.inference.server import InferenceServer
    from cloud_server_tpu.models import transformer
    from cloud_server_tpu.models.quantization import quantize_params

    base = ModelConfig(
        vocab_size=32000, embed_dim=1024, num_layers=16, num_heads=16,
        num_kv_heads=16, head_dim=64, mlp_dim=4096, max_seq_len=1024,
        dtype="bfloat16", param_dtype="float32", remat="none")
    infer_cfg = InferConfig(max_decode_len=900, temperature=1.0,
                            eos_token_id=-1, pad_token_id=0)
    params_bf16 = transformer.init_params(base, jax.random.key(0))
    params_int8 = quantize_params(params_bf16)
    _rng = np.random.RandomState(7)
    plain_prompts = [[int(x) for x in _rng.randint(1, 30000, size=64)]
                     for _ in range(8)]
    # repetitive prompts: the n-gram speculative sweet spot (code/tables)
    rep_prompts = [([3, 17, 9, 4] * 16)[:64] for _ in range(8)]
    greedy = dataclasses.replace(infer_cfg, temperature=0.0)

    chunk = 32
    out = {}

    def run_contiguous(tag, params, kv):
        cfg = dataclasses.replace(base, kv_cache_dtype=kv)
        srv = InferenceServer(params, cfg, infer_cfg, max_slots=8,
                              max_len=1024, prompt_buckets=[64],
                              decode_chunk=chunk)
        for p in plain_prompts:
            srv.submit(p, max_new_tokens=900)
        for _ in range(3):
            srv.step()
        before = srv.tokens_emitted
        t0 = time.perf_counter()
        for _ in range(8):
            srv.step()
        dt = time.perf_counter() - t0
        out[tag] = (srv.tokens_emitted - before) / dt
        print(f"[serving_bench] {tag}: {out[tag]:.1f}", flush=True)
        srv.stop()

    def run_paged(tag, params, kv, *, spec=0, prompts=plain_prompts,
                  icfg=None, sampling=None):
        cfg = dataclasses.replace(base, kv_cache_dtype=kv,
                                  decode_attention_impl="pallas")
        srv = PagedInferenceServer(
            params, cfg, icfg or infer_cfg, max_slots=8, max_context=1024,
            page_size=128, prefill_chunk=256, decode_chunk=chunk,
            spec_drafts=spec, prompt_buckets=[64, 128])
        for i, p in enumerate(prompts):
            srv.submit(p, max_new_tokens=880,
                       sampling=sampling(i) if sampling else None)
        for _ in range(3):
            srv.step()
        before = srv.tokens_emitted
        r0, c0 = srv.decode_rounds, srv.decode_tokens_committed
        t0 = time.perf_counter()
        for _ in range(8):
            srv.step()
        dt = time.perf_counter() - t0
        out[tag] = (srv.tokens_emitted - before) / dt
        print(f"[serving_bench] {tag}: {out[tag]:.1f}", flush=True)
        if spec:
            rounds = srv.decode_rounds - r0
            out[tag + "_accept"] = ((srv.decode_tokens_committed - c0)
                                    / max(rounds, 1))
        srv.stop()

    run_contiguous("decode_tok_s_xla_bf16", params_bf16, "model")
    run_contiguous("decode_tok_s_xla_int8", params_int8, "model")
    run_contiguous("decode_tok_s_xla_bf16_kvint8", params_bf16, "int8")
    run_paged("decode_tok_s_pallas_bf16", params_bf16, "model")
    # A/B for the per-request-sampling hot path: SamplingParams(seed=i)
    # forces the SamplingRows decode dispatch with math identical to the
    # server default (temperature 1.0) — the tok/s delta vs the row
    # above IS the rows-mode overhead (r4 shipped the rows threading
    # with a correctness test but no on-chip timing)
    from cloud_server_tpu.inference.sampling import SamplingParams
    run_paged("decode_tok_s_pallas_rows_on", params_bf16, "model",
              sampling=lambda i: SamplingParams(seed=1000 + i))
    run_paged("decode_tok_s_pallas_bf16_kvint8", params_bf16, "int8")
    # speculative: greedy so acceptance reflects the model, not sampling
    run_paged("decode_tok_s_pallas_spec_repeat", params_bf16, "model",
              spec=3, prompts=rep_prompts, icfg=greedy)
    run_paged("decode_tok_s_pallas_spec_random", params_bf16, "model",
              spec=3, prompts=plain_prompts, icfg=greedy)
    # churn rides in this section (reuses the params already on device)
    # — guarded so a churn-time tunnel flake cannot void the headline
    # decode rows measured above
    try:
        out.update(_admission_churn_bench(params_bf16, base, infer_cfg))
    except Exception as exc:  # noqa: BLE001
        print(f"[serving_bench] churn skipped after error: {exc!r}",
              flush=True)
        out["churn_error"] = repr(exc)[:160]
    # async double-buffered scheduler A/B on the same mix (item 4's
    # acceptance measurement; same guard discipline)
    try:
        out.update(_overlap_churn_bench(params_bf16, base, infer_cfg))
    except Exception as exc:  # noqa: BLE001
        print(f"[serving_bench] churn_overlap skipped after error: "
              f"{exc!r}", flush=True)
        out["churn_overlap_error"] = repr(exc)[:160]
    # speculation-under-churn three-way A/B (same guard discipline)
    try:
        out.update(_spec_churn_bench(params_bf16, base, infer_cfg))
    except Exception as exc:  # noqa: BLE001
        print(f"[serving_bench] churn_spec skipped after error: "
              f"{exc!r}", flush=True)
        out["churn_spec_error"] = repr(exc)[:160]
    # multi-tenant QoS isolation A/B (same guard discipline)
    try:
        out.update(_qos_isolation_bench(params_bf16, base, infer_cfg))
    except Exception as exc:  # noqa: BLE001
        print(f"[serving_bench] qos_isolation skipped after error: "
              f"{exc!r}", flush=True)
        out["qos_isolation_error"] = repr(exc)[:160]
    # shared-prefix cache churn under a multi-tenant flood (same guard)
    try:
        out.update(_prefix_cache_churn_bench(params_bf16, base,
                                             infer_cfg))
    except Exception as exc:  # noqa: BLE001
        print(f"[serving_bench] prefix_cache_churn skipped after "
              f"error: {exc!r}", flush=True)
        out["prefix_cache_churn_error"] = repr(exc)[:160]
    # fleet fault recovery: kill one replica mid-flood (same guard)
    try:
        out.update(_fault_recovery_bench(params_bf16, base, infer_cfg))
    except Exception as exc:  # noqa: BLE001
        print(f"[serving_bench] fault_recovery skipped after error: "
              f"{exc!r}", flush=True)
        out["fault_recovery_error"] = repr(exc)[:160]
    # disaggregated prefill/decode fleet A/B — the headline
    # role-specialization measurement (same guard discipline)
    try:
        out.update(_disagg_bench(params_bf16, base, infer_cfg))
    except Exception as exc:  # noqa: BLE001
        print(f"[serving_bench] disagg_vs_colocated skipped after "
              f"error: {exc!r}", flush=True)
        out["disagg_vs_colocated_error"] = repr(exc)[:160]
    # anomaly watchdog + tail retention under an injected-fault flood
    # (same guard discipline)
    try:
        out.update(_anomaly_forensics_bench(params_bf16, base,
                                            infer_cfg))
    except Exception as exc:  # noqa: BLE001
        print(f"[serving_bench] anomaly_forensics skipped after "
              f"error: {exc!r}", flush=True)
        out["anomaly_forensics_error"] = repr(exc)[:160]
    # SLO-burn autoscaler vs static fleet on the diurnal-burst scenario
    # (same guard discipline)
    try:
        out.update(_slo_autoscale_bench(params_bf16, base, infer_cfg))
    except Exception as exc:  # noqa: BLE001
        print(f"[serving_bench] slo_autoscale skipped after "
              f"error: {exc!r}", flush=True)
        out["slo_autoscale_error"] = repr(exc)[:160]
    return out


def _fault_recovery_bench(params, base, infer_cfg):
    """Fleet fault recovery A/B (docs/serving.md "Fault tolerance"):
    a 2-replica router floods 16 requests; the injected arm arms a
    deterministic dispatch fault on replica 0 a few iterations in —
    its scheduler crashes exactly like a poisoned device program —
    and the run reports how the failure-domain layer absorbed it:

      * `fault_recovery_time_to_breaker_open_ms` — injected fault ->
        replica-0 breaker open (placement stops routing there);
      * `fault_recovery_retry_success_rate` — zero-token failed
        requests resubmitted to replica 1 that completed normally
        (the safe-retry rule);
      * `fault_recovery_migration_success_rate`, `..._migration_ms_p50`
        and `..._tokens_salvaged_frac` — the mid-stream kills: requests
        that had already streamed tokens are LIVE-MIGRATED (host state
        salvaged, resumed token-exact on replica 1) instead of failing
        fast; salvaged-frac is the share of the migrated requests'
        decode budget carried over rather than regenerated;
      * `fault_recovery_{baseline,injected}_completed_frac`,
        `..._slo_ttft` and `..._slo_itl` — the client-visible blast
        radius vs the uninjected control at identical load.

    Both arms run twice (untimed compile warm-up, then measured),
    like the churn benches."""
    import dataclasses

    import numpy as np

    from cloud_server_tpu.inference.faults import FaultPlan
    from cloud_server_tpu.inference.paged_server import PagedInferenceServer
    from cloud_server_tpu.inference.router import ReplicatedRouter

    cfg = dataclasses.replace(base, decode_attention_impl="pallas")
    slo_cfg = {"windows_s": [300],
               "classes": {"default": {"objective": 0.99, "ttft_s": 5.0,
                                       "itl_s": 2.0, "e2e_s": 600.0}}}
    max_new = 96

    def scenario(inject: bool):
        fp = FaultPlan() if inject else None

        def mk(faults):
            return PagedInferenceServer(
                params, cfg, infer_cfg, max_slots=8, max_context=1024,
                page_size=128, prefill_chunk=256, decode_chunk=8,
                prompt_buckets=[64, 256], slo=slo_cfg, faults=faults)

        router = ReplicatedRouter([mk(fp), mk(None)],
                                  breaker_threshold=3,
                                  breaker_reset_s=600.0)
        rng = np.random.RandomState(0)
        reqs = [router.submit([int(x) for x in
                               rng.randint(1, 30000, size=64)],
                              max_new_tokens=max_new)
                for _ in range(16)]
        for _ in range(4):
            router.step()
        t_fault = t_open = None
        if inject:
            fp.arm("dispatch", count=1)
            t_fault = time.perf_counter()
        deadline = time.perf_counter() + 300
        while (not all(r.done for r in reqs)
               and time.perf_counter() < deadline):
            router.step()
            if (inject and t_open is None
                    and router.breaker_states()[0]["state"] == "open"):
                t_open = time.perf_counter()
        ok = sum(1 for r in reqs
                 if r.done
                 and not (r.finish_reason or "").startswith("error"))
        rep = router.slo_report()
        mets = rep["classes"]["default"]["metrics"]

        def attainment(name):
            a = mets.get(name, {}).get("lifetime", {}).get("attainment")
            return 1.0 if a is None else a

        snap = router.metrics_snapshot()
        res = {"completed_frac": ok / len(reqs),
               "slo_ttft": attainment("ttft"),
               "slo_itl": attainment("itl")}
        if inject:
            res["time_to_breaker_open_ms"] = (
                -1.0 if t_open is None else (t_open - t_fault) * 1e3)
            retries = snap["cloud_server_router_retries_total"]["value"]
            succ = snap["cloud_server_router_retry_success_total"][
                "value"]
            res["retries"] = retries
            res["retry_success_rate"] = succ / max(retries, 1)
            # the mid-stream half of the kill: live migrations
            from cloud_server_tpu.utils.serving_metrics import \
                histogram_percentile
            mig = router.migration_stats()
            hist = snap.get("cloud_server_migration_ms")
            res["migrations"] = mig["out_started"]
            res["migration_success_rate"] = mig["success_rate"]
            res["migration_ms_p50"] = (
                histogram_percentile(hist, 0.50)
                if hist and hist.get("count") else -1.0)
            res["tokens_salvaged_frac"] = (
                mig["tokens_salvaged"]
                / max(mig["in_completed"] * max_new, 1))
        for r in reqs:
            r.cancel()
        router.run_until_idle()
        router.stop()
        return res

    out = {}
    for tag, inject in (("baseline", False), ("injected", True)):
        scenario(inject)  # warm-up: compile every shape
        res = scenario(inject)
        out[f"fault_recovery_{tag}_completed_frac"] = \
            res["completed_frac"]
        out[f"fault_recovery_{tag}_slo_ttft"] = res["slo_ttft"]
        out[f"fault_recovery_{tag}_slo_itl"] = res["slo_itl"]
        if inject:
            out["fault_recovery_time_to_breaker_open_ms"] = \
                res["time_to_breaker_open_ms"]
            out["fault_recovery_retries"] = res["retries"]
            out["fault_recovery_retry_success_rate"] = \
                res["retry_success_rate"]
            out["fault_recovery_migrations"] = res["migrations"]
            out["fault_recovery_migration_success_rate"] = \
                res["migration_success_rate"]
            out["fault_recovery_migration_ms_p50"] = \
                res["migration_ms_p50"]
            out["fault_recovery_tokens_salvaged_frac"] = \
                res["tokens_salvaged_frac"]
        print(f"[serving_bench] fault_recovery_{tag}: completed "
              f"{res['completed_frac']:.2f}, slo_ttft "
              f"{res['slo_ttft']:.3f}, slo_itl {res['slo_itl']:.3f}"
              + (f", breaker open in "
                 f"{res['time_to_breaker_open_ms']:.1f} ms, retry "
                 f"success {res['retry_success_rate']:.2f}, "
                 f"{res['migrations']} migrations (success "
                 f"{res['migration_success_rate']:.2f}, p50 "
                 f"{res['migration_ms_p50']:.1f} ms, salvaged "
                 f"{res['tokens_salvaged_frac']:.2f})"
                 if inject else ""), flush=True)
    return out


def _anomaly_forensics_bench(params, base, infer_cfg):
    """Anomaly watchdog + tail retention + forensic bundles under a
    churn flood with injected faults (docs/observability.md "Anomaly
    detection & forensics"), at trace_sample_rate=0.01 — the
    production-shaped sampling where head sampling alone would lose
    ~99% of broken requests' traces:

      * three incident rounds: each arms an `iteration_stall` fault
        (faults.py — the scheduler stalls mid-iteration) and lands a
        burst of deadline-doomed requests; `anomaly_detect_ms_p50` is
        the wall time from the burst to the watchdog's activation
        edge (`deadline_spike` latching), per round;
      * `bundle_on_anomaly` auto-captures a forensic bundle on each
        edge — asserted captured, carrying the covering flight
        window;
      * `churn_tail_traces_retained_frac` — the fraction of broken
        (deadline-expired) requests whose span trees survived at 1%
        head sampling via tail retention, asserted 1.0 with every
        retained tree gap-free (phase spans contiguous)."""
    import dataclasses

    from cloud_server_tpu.inference.faults import FaultPlan
    from cloud_server_tpu.inference.paged_server import PagedInferenceServer
    from cloud_server_tpu.inference.request_trace import PHASES

    cfg = dataclasses.replace(base, decode_attention_impl="pallas")
    icfg = dataclasses.replace(
        infer_cfg, trace_sample_rate=0.01, trace_tail_capacity=256,
        bundle_on_anomaly=True)
    # short windows so each incident round opens (and closes) its OWN
    # anomaly window: three distinct activation edges, three bundles
    anomaly_cfg = {"warmup": 0, "check_every": 1, "hold_s": 0.25,
                   "rules": {"deadline_spike":
                             {"count": 3, "window_s": 1.0}}}
    fp = FaultPlan()
    srv = PagedInferenceServer(
        params, cfg, icfg, max_slots=16, max_context=1024,
        page_size=128, prefill_chunk=256, decode_chunk=8,
        prompt_buckets=[64, 256], scheduler="mixed",
        anomaly=anomaly_cfg, faults=fp)
    mk_prompt = make_prompt_fn(0)

    def feed():
        # the watchdog only observes BUSY iterations, so keep the
        # scheduler fed (window close needs observed time to pass) —
        # the shared top-up helper (utils/bench_helpers)
        top_up(srv, mk_prompt)

    # background churn flood at 1% head sampling; a few steps compile
    # every shape before the timed incident rounds
    flood = [srv.submit(mk_prompt(64), max_new_tokens=256)
             for _ in range(8)]
    for _ in range(4):
        srv.step()

    detect_ms = []
    detect_steps = []
    doomed = []
    fired_seen = 0
    for _ in range(3):
        fp.arm("iteration_stall", count=2, stall_ms=120.0)
        doomed_batch = [srv.submit(mk_prompt(64), max_new_tokens=64,
                                   deadline_s=1e-3) for _ in range(3)]
        doomed += doomed_batch
        t0 = time.perf_counter()
        steps = 0
        while time.perf_counter() - t0 < 60.0:
            feed()
            srv.step()
            steps += 1
            fired = sum(srv.anomaly_stats()["fired_total"].values())
            if fired > fired_seen:
                fired_seen = fired
                detect_ms.append((time.perf_counter() - t0) * 1e3)
                detect_steps.append(steps)
                break
        # step the open window shut before the next round (prune past
        # window_s, then hold_s of recovery)
        t_close = time.perf_counter()
        while (srv.anomaly_stats()["active"]
               and time.perf_counter() - t_close < 60.0):
            feed()
            srv.step()
    assert len(detect_ms) == 3, (
        f"watchdog latched {len(detect_ms)}/3 incident rounds")
    assert max(detect_steps) <= 50, (
        f"detection took {max(detect_steps)} iterations — not bounded")
    srv.run_until_idle()

    # injected fault really fired, bundles auto-captured on each edge
    # with the covering flight window
    fstats = srv.fault_stats()
    assert fstats["fired"]["iteration_stall"] >= 1, fstats["fired"]
    bundles = srv.debug_bundles()
    assert len(bundles) == 3, f"{len(bundles)} bundles for 3 edges"
    for b in bundles:
        assert b["trigger"] == "anomaly:deadline_spike"
        assert b["flight"], "bundle missing the covering flight window"
        assert b["anomaly"]["active"], "bundle missed the open window"

    # 100% of broken requests kept a gap-free tree at 1% head sampling
    # (lookup spans the head ring AND the tail ring — a doomed request
    # that happened to be head-sampled counts too)
    retained = 0
    for r in doomed:
        assert r.finish_reason == "deadline", r.finish_reason
        tree = srv.lookup_trace(r.request_id)
        if tree is None:
            continue
        retained += 1
        root = tree["root"]
        assert root["start"] == r.submit_time
        assert root["end"] is not None
        phases = [c for c in root["children"] if c["name"] in PHASES]
        assert phases[0]["start"] == root["start"]
        for a, b in zip(phases, phases[1:]):
            assert a["end"] == b["start"], \
                f"gap between {a['name']} and {b['name']}"
        assert phases[-1]["end"] == root["end"]
    frac = retained / len(doomed)
    assert frac == 1.0, (
        f"only {retained}/{len(doomed)} broken requests kept a tree")
    tstats = srv.tail_trace_stats()
    srv.stop()

    out = {"churn_tail_traces_retained_frac": frac,
           "anomaly_detect_ms_p50": pct(detect_ms, 0.50),
           "anomaly_detect_iters_max": max(detect_steps),
           "anomaly_bundles_captured": len(bundles),
           "anomaly_tail_retained_total":
               sum(tstats["retained_total"].values())}
    print(f"[serving_bench] anomaly_forensics: detect p50 "
          f"{out['anomaly_detect_ms_p50']:.1f} ms "
          f"(<= {out['anomaly_detect_iters_max']} iters), "
          f"{len(bundles)} bundles, tail retained frac {frac:.2f}",
          flush=True)
    return out


def _slo_autoscale_bench(params, base, infer_cfg):
    """SLO-burn autoscaler vs static fleet on the canonical
    quiet->burst->quiet diurnal scenario (scenarios.diurnal_burst),
    replayed by the scenario harness against two live fleets:

      * AUTOSCALED — starts at min_replicas=1 with a warm pool of
        spares; the SLOBurnAutoscaler polls fleet burn rates +
        pending depth and calls add_replica/remove_replica(migrate).
      * STATIC — a fixed fleet sized to the autoscaled arm's AVERAGE
        footprint rounded UP (ceil of chip-seconds / wall time), so
        the control spends at least as many chip-seconds. Equal-ish
        chip-seconds is the fairness control: the autoscaler's only
        edge is placing capacity WHEN the burst needs it.

    Reported: per-arm interactive attainment (worst lifetime metric
    from slo_report, removed replicas' trackers merged back in so
    scale-downs cannot drop history), chip-seconds, scale-up/down
    counts, and time-to-recover (burst start -> first scale-up).

    ASSERTS the acceptance bar: autoscaled interactive attainment >=
    static at chip-seconds <= static x 1.05, at least one scale-up
    AND one scale-down actually fired, and ZERO lost requests — every
    fired event completes (scale-down drains migrate, never drop)."""
    import dataclasses
    import math

    from cloud_server_tpu.inference.paged_server import PagedInferenceServer
    from cloud_server_tpu.inference.router import ReplicatedRouter
    from cloud_server_tpu.inference.slo import merge_reports
    from cloud_server_tpu.scenarios import (AutoscalerConfig, ReplayDriver,
                                            SLOBurnAutoscaler, TenantMix,
                                            diurnal_burst)

    # same rationale as _disagg_bench: the A/B contrast is within-run,
    # so xla off-TPU keeps the CPU-sandbox asserts tractable
    impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    cfg = dataclasses.replace(base, decode_attention_impl=impl)
    qos_cfg = {"quantum": 64,
               "tenants": {
                   "inter": {"weight": 4.0, "priority": "interactive"},
                   "bulk": {"weight": 1.0, "priority": "batch"}}}
    # short windows so burn reacts within a ~40 s bench; targets sized
    # to pass when a request is served promptly and fail when it sits
    # behind an unscaled burst backlog
    slo_cfg = {"windows_s": [5, 15],
               "classes": {
                   "interactive": {"objective": 0.9, "ttft_s": 6.0,
                                   "queue_wait_s": 5.0, "itl_s": 3.0,
                                   "e2e_s": 60.0},
                   "batch": {"objective": 0.5, "ttft_s": 20.0,
                             "e2e_s": 120.0}}}
    phase_s = 12.0
    scenario = diurnal_burst(
        seed=0, duration_s=3 * phase_s, phase_s=phase_s,
        low_rps=0.2, high_rps=3.0,
        tenants=TenantMix({"inter": 3.0, "bulk": 1.0}))

    def mk():
        return PagedInferenceServer(
            params, cfg, infer_cfg, max_slots=8, max_context=1024,
            page_size=128, prefill_chunk=256, decode_chunk=8,
            prompt_buckets=[64, 256], qos=qos_cfg, slo=slo_cfg)

    def interactive_attainment(reports) -> float:
        rep = merge_reports(reports)
        centry = (rep or {}).get("classes", {}).get("interactive")
        if not centry:
            return 1.0
        vals = [m["lifetime"]["attainment"]
                for m in centry["metrics"].values()
                if m["lifetime"]["total"]]
        return min(vals) if vals else 1.0

    def run_arm(n_start, asc_pool):
        router = ReplicatedRouter([mk() for _ in range(n_start)])
        released = []
        asc = None
        if asc_pool is not None:
            spares = [mk() for _ in range(asc_pool)]
            asc = SLOBurnAutoscaler(
                router, spawn=lambda role: (spares.pop()
                                            if spares else None),
                release=released.append,
                config=AutoscalerConfig(
                    min_replicas=1, max_replicas=1 + asc_pool,
                    classes=("interactive", "batch", "default"),
                    up_fast_burn=1.5, up_slow_burn=1.0,
                    down_fast_burn=0.5, down_slow_burn=0.5,
                    pending_high=4.0, pending_low=1.0,
                    hold_s=4.0, poll_s=0.5, drain_timeout_s=60.0))
        drv = ReplayDriver(router, scenario.generate())
        state = {"t": time.monotonic(), "chips": 0.0, "poll": 0.0}
        t_start = state["t"]

        def pump():
            router.step()
            now = time.monotonic()
            state["chips"] += (len(router.attached_indices())
                               * (now - state["t"]))
            state["t"] = now
            if asc is not None and now - state["poll"] >= asc.cfg.poll_s:
                state["poll"] = now
                asc.step(now)

        res = drv.run(step=pump, timeout_s=600.0)
        router.run_until_idle()
        # the chip-second account covers the SERVING window only (both
        # arms pay for capacity held while requests could arrive/run);
        # freeze it here so the settle wait below is not billed
        t_end = time.monotonic()
        state["chips"] += (len(router.attached_indices())
                           * (t_end - state["t"]))
        state["t"] = t_end
        chips = state["chips"]
        elapsed = t_end - t_start
        # post-drain settle: let the quiet tail's scale-down land (the
        # burn windows need wall time to age the burst out)
        if asc is not None:
            t_settle = time.monotonic()
            while (len(router.attached_indices()) > 1
                   and time.monotonic() - t_settle < 30.0):
                pump()
                time.sleep(0.05)
        reports = [r.slo_report() for r in router.replicas
                   if hasattr(r, "slo_report")]
        # scale-downs detach trackers from the fleet report — merge the
        # released replicas back so attainment covers EVERY request
        reports += [r.slo_report() for r in released]
        att = interactive_attainment(reports)
        stats = asc.stats() if asc is not None else None
        events = list(asc.events) if asc is not None else []
        if asc is not None:
            asc.stop()
        for r in released:
            r.stop()
        router.stop()
        return {"res": res, "att": att, "chips": chips,
                "elapsed": elapsed, "stats": stats, "events": events,
                "t_start": t_start}

    # one throwaway replica warms the jit cache so neither arm pays
    # compile time inside its measured window
    warm = mk()
    mk_prompt = make_prompt_fn(0)
    warm.submit(mk_prompt(64), max_new_tokens=8, tenant="inter")
    warm.submit(mk_prompt(200), max_new_tokens=8, tenant="bulk")
    warm.run_until_idle()
    warm.stop()

    auto = run_arm(1, asc_pool=2)
    n_static = max(1, math.ceil(auto["chips"] / auto["elapsed"] - 1e-6))
    static = run_arm(n_static, asc_pool=None)

    ups = [e for e in auto["events"] if e.action == "up"]
    downs = [e for e in auto["events"] if e.action == "down"]
    recover_s = (max(0.0, ups[0].t - (auto["t_start"] + phase_s))
                 if ups else -1.0)
    out = {
        "slo_autoscale_auto_attainment": auto["att"],
        "slo_autoscale_static_attainment": static["att"],
        "slo_autoscale_auto_chip_s": auto["chips"],
        "slo_autoscale_static_chip_s": static["chips"],
        "slo_autoscale_static_replicas": n_static,
        "slo_autoscale_scale_ups": len(ups),
        "slo_autoscale_scale_downs": len(downs),
        "slo_autoscale_time_to_recover_s": recover_s,
        "slo_autoscale_lost_requests": (auto["res"]["failed"]
                                        + auto["res"]["outstanding"]
                                        + auto["res"]["rejected"]),
    }
    assert out["slo_autoscale_lost_requests"] == 0, (
        f"autoscaled arm lost requests: {auto['res']}")
    assert ups and downs, (
        f"autoscaler never cycled: {len(ups)} ups, {len(downs)} downs "
        f"(events: {[e.to_json() for e in auto['events']]})")
    assert auto["att"] >= static["att"], (
        f"autoscaled interactive attainment {auto['att']:.3f} < static "
        f"{static['att']:.3f} at n_static={n_static}")
    assert auto["chips"] <= static["chips"] * 1.05, (
        f"autoscaled burned more chip-seconds ({auto['chips']:.1f}) "
        f"than the static control ({static['chips']:.1f})")
    print(f"[serving_bench] slo_autoscale: auto attain "
          f"{auto['att']:.3f} ({auto['chips']:.0f} chip-s, "
          f"{len(ups)} up/{len(downs)} down, recover "
          f"{recover_s:.1f} s) vs static[{n_static}] "
          f"{static['att']:.3f} ({static['chips']:.0f} chip-s)",
          flush=True)
    return out


def _disagg_bench(params, base, infer_cfg):
    """Disaggregated prefill/decode A/B at EQUAL replica count
    (docs/serving.md "Disaggregated serving"): two identical
    2-replica fleets serve the same schedule — an interactive tenant
    decoding steadily while a batch tenant drip-feeds long prompts —
    one fleet colocated (role-less control), one role-specialized
    (1 prefill + 1 decode; interactive requests hand off after
    prefill). Reported:

      * `disagg_{colo,spec}_itl_ms_p99` — interactive inter-token
        p99: the specialized decode replica never runs an admission
        chunk, so the flood's prefill bursts stop landing in the
        interactive requests' token gaps;
      * `disagg_{colo,spec}_ttft_ms_p99` — interactive TTFT p99 (the
        guard: role-specialization must not regress first-token
        latency);
      * `disagg_handoffs` / `disagg_handoff_success_rate` — admitted
        continuations over attempted handoffs;
      * `disagg_itl_p99_ratio` — spec/colo (headline; < 1 is a win).

    Beyond the numbers the measured run ASSERTS the acceptance bar:
    strict interactive ITL p99 improvement, TTFT p99 within noise of
    the control, handoff success rate >= 0.95, and every handed-off
    request reading as exactly ONE gap-free span tree spanning both
    replicas (prefill half + `migrate_gap` seam + decode half).
    Both arms run twice (small untimed compile warm-up, then
    measured), like the other serving A/Bs."""
    import dataclasses

    from cloud_server_tpu.inference.paged_server import PagedInferenceServer
    from cloud_server_tpu.inference.request_trace import PHASES
    from cloud_server_tpu.inference.router import ReplicatedRouter

    # the A/B is within-fleet, so the attention kernel choice is
    # orthogonal to the contrast being measured; xla off-TPU keeps the
    # CPU-sandbox run (where the acceptance asserts fire) tractable —
    # pallas-interpret pays ~25 s compile PER SHAPE there
    impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    cfg = dataclasses.replace(base, decode_attention_impl=impl)
    qos_cfg = {"quantum": 64,
               "tenants": {
                   "inter": {"weight": 4.0, "priority": "interactive"},
                   "bulk": {"weight": 1.0, "priority": "batch"}}}

    def scenario(roles, inter_new, n_flood, check):
        def mk():
            return PagedInferenceServer(
                params, cfg, infer_cfg, max_slots=8, max_context=1024,
                page_size=128, prefill_chunk=256, decode_chunk=8,
                prompt_buckets=[64, 256], qos=qos_cfg, tracing=1.0)

        router = ReplicatedRouter([mk(), mk()], roles=roles)
        mk_prompt = make_prompt_fn(0)

        def handoffs_attempted():
            return router.metrics_snapshot()[
                "cloud_server_router_handoffs_total"]["value"]

        inter = [router.submit(mk_prompt(64), max_new_tokens=inter_new,
                               tenant="inter") for _ in range(6)]
        # settle: admission and (spec arm) every handoff complete
        # BEFORE the flood starts, so the seam gaps sit in the warm
        # window and the measured contrast is pure admission
        # interference
        for _ in range(12):
            router.step()
        if roles is not None:
            t_settle = time.perf_counter() + 30
            while (time.perf_counter() < t_settle
                   and handoffs_attempted() < len(inter)
                   and not all(r.done for r in inter)):
                router.step()
        flood = []
        steps = 0
        deadline = time.perf_counter() + 300
        # len() guard: the flood must fully submit even when the
        # interactive side already finished (the short warm-up runs),
        # or the warm-up never compiles the mixed admission shapes
        while ((len(flood) < n_flood
                or not all(r.done for r in inter + flood))
               and time.perf_counter() < deadline):
            # drip-feed: admission chunks keep landing for as long as
            # the interactive requests decode (the colocated fleet's
            # pain; one-shot floods finish admitting in a few steps)
            if steps % 2 == 0 and len(flood) < n_flood:
                flood += [router.submit(mk_prompt(256),
                                        max_new_tokens=24,
                                        tenant="bulk")
                          for _ in range(2)]
            router.step()
            steps += 1

        itl = [b - a for r in inter
               for a, b in zip(r.emit_times, r.emit_times[1:])]
        ttft = [r.emit_times[0] - r.submit_time for r in inter
                if r.emit_times]
        reqs = inter + flood
        res = {"itl_ms_p99": pct(itl, 0.99) * 1e3,
               "ttft_ms_p99": pct(ttft, 0.99) * 1e3,
               "completed_frac": sum(r.finish_reason == "length"
                                     for r in reqs) / len(reqs)}
        if roles is not None:
            snap = router.metrics_snapshot()
            att = snap["cloud_server_router_handoffs_total"]["value"]
            succ = snap["cloud_server_router_handoff_success_total"][
                "value"]
            res["handoffs"] = att
            res["handoff_success_rate"] = succ / max(att, 1)
        if check and roles is not None:
            # acceptance: EVERY handed-off request reads as exactly
            # ONE gap-free span tree spanning prefill -> decode
            trees = router.trace_trees()
            merged = [t for t in trees
                      if t["root"]["tags"].get("handoff_segments")]
            assert merged, "no handoff produced a merged span tree"
            by_id = {}
            for t in trees:
                by_id.setdefault(t["request_id"], []).append(t)
            for t in merged:
                assert len(by_id[t["request_id"]]) == 1, \
                    f"duplicate trees for {t['request_id']}"
                root = t["root"]
                tags = root["tags"]
                assert tags.get("decode_replica") is not None \
                    and tags["decode_replica"] != tags.get("replica"), \
                    tags
                assert root["end"] is not None, "unfinished merge"
                phases = [c for c in root["children"]
                          if c["name"] in PHASES]
                assert "migrate_gap" in [p["name"] for p in phases]
                assert phases[0]["start"] == root["start"]
                for a, b in zip(phases, phases[1:]):
                    assert a["end"] == b["start"], \
                        f"gap between {a['name']} and {b['name']}"
                assert phases[-1]["end"] == root["end"]
            # consumed continuations never leak as standalone trees
            assert not [t for t in trees
                        if t["root"]["tags"].get("handoff_of")], \
                "unmerged handoff continuation leaked"
        for r in inter + flood:
            r.cancel()
        router.run_until_idle()
        router.stop()
        return res

    out = {}
    for tag, roles in (("colo", None), ("spec", ["prefill", "decode"])):
        # warm-up runs the FULL workload shape (same flood count and
        # drip, short decode budgets): every mixed-step / continuation
        # admission variant compiles here, so no compile stall can
        # masquerade as an ITL gap in the measured run
        scenario(roles, 48, 12, check=False)
        res = scenario(roles, 256, 12, check=True)
        out[f"disagg_{tag}_itl_ms_p99"] = res["itl_ms_p99"]
        out[f"disagg_{tag}_ttft_ms_p99"] = res["ttft_ms_p99"]
        out[f"disagg_{tag}_completed_frac"] = res["completed_frac"]
        if roles is not None:
            out["disagg_handoffs"] = res["handoffs"]
            out["disagg_handoff_success_rate"] = \
                res["handoff_success_rate"]
        print(f"[serving_bench] disagg_{tag}: itl p99 "
              f"{res['itl_ms_p99']:.1f} ms, ttft p99 "
              f"{res['ttft_ms_p99']:.1f} ms, completed "
              f"{res['completed_frac']:.2f}"
              + (f", {res['handoffs']:.0f} handoffs (success "
                 f"{res['handoff_success_rate']:.2f})"
                 if roles is not None else ""), flush=True)
    out["disagg_itl_p99_ratio"] = (
        out["disagg_spec_itl_ms_p99"]
        / max(out["disagg_colo_itl_ms_p99"], 1e-9))
    # the acceptance bar, asserted where the numbers were measured
    assert out["disagg_handoffs"] >= 1, "no handoff ever attempted"
    assert out["disagg_handoff_success_rate"] >= 0.95, out
    assert (out["disagg_spec_itl_ms_p99"]
            < out["disagg_colo_itl_ms_p99"]), (
        "role-specialization did not improve interactive ITL p99: "
        f"{out}")
    # TTFT: no regression, within CPU-sandbox timer noise
    assert (out["disagg_spec_ttft_ms_p99"]
            <= out["disagg_colo_ttft_ms_p99"] * 1.10 + 25.0), (
        f"role-specialization regressed interactive TTFT p99: {out}")
    print(f"[serving_bench] disagg_itl_p99_ratio "
          f"{out['disagg_itl_p99_ratio']:.2f}", flush=True)
    return out


def _prefix_cache_churn_bench(params, base, infer_cfg):
    """Prefix-cache behavior under multi-tenant churn — the
    measurement half of ROADMAP item 3 (the policy half, prefix-aware
    routing + per-tenant quotas, will A/B against these keys as
    `prefix_cache_speedup`).

    Scenario: two tenants share one SYSTEM PROMPT (a 256-token header,
    exactly the fleet shape the radix cache exists for) and submit
    short unique continuations, while a third "flood" tenant streams
    pairwise-disjoint long prompts through a pool sized so the flood's
    churn must evict cached chains. Reports the page hit rate, the
    eviction rate per 1k emitted tokens, and the per-tenant
    saved-token split — plus asserts the attribution layer end-to-end:
    the shared header must be the hottest sketch chain, both header
    tenants must realize savings, and the flood tenant must show up
    as the eviction FORCER in the forensics matrix."""
    import dataclasses

    import numpy as np

    from cloud_server_tpu.inference.paged_server import PagedInferenceServer

    cfg = dataclasses.replace(base, decode_attention_impl="pallas")
    qos_cfg = {"tenants": {"team-a": {}, "team-b": {},
                           "flood": {"priority": "batch"}}}

    def scenario():
        # 44 pages x 128 tokens: the 12 disjoint 384-token flood
        # chains alone (~36 keyed pages) roll the cache over, so the
        # flood FORCES evictions while the LRU protects the re-hit
        # shared header — exactly the churn-vs-locality regime item
        # 3's quota policy will tune
        srv = PagedInferenceServer(
            params, cfg, infer_cfg, max_slots=16, max_context=1024,
            page_size=128, prefill_chunk=256, decode_chunk=8,
            prompt_buckets=[64, 256, 512], num_pages=44, qos=qos_cfg)
        rng = np.random.RandomState(7)
        header = [int(x) for x in rng.randint(1, 30000, size=256)]

        def flood_prompt():
            return [int(x) for x in rng.randint(1, 30000, size=384)]

        t0 = time.perf_counter()
        reqs = []
        for wave in range(4):
            for tenant in ("team-a", "team-b"):
                reqs += [srv.submit(header + [100 + wave, i],
                                    max_new_tokens=32, tenant=tenant)
                         for i in range(2)]
            reqs += [srv.submit(flood_prompt(), max_new_tokens=32,
                                tenant="flood") for _ in range(3)]
            for _ in range(6):
                srv.step()
        srv.run_until_idle()
        dt = time.perf_counter() - t0
        total = sum(len(r.tokens) for r in reqs)
        cs = srv.cache_stats()
        evictions = srv.allocator.evictions
        srv.stop()
        return cs, total, dt, evictions

    scenario()  # warm-up: compile every prefill/decode shape
    cs, total, dt, evictions = scenario()
    led = cs["tenants"]
    # end-to-end attribution asserts (guarded like the churn asserts)
    assert cs["prefix"]["hit_pages"] > 0, "shared header never hit"
    assert led["team-a"]["saved_tokens"] > 0, "team-a realized nothing"
    assert led["team-b"]["saved_tokens"] > 0, "team-b realized nothing"
    assert cs["top_prefixes"], "hot-prefix sketch is empty"
    # the 256-token header is 2 pages deep at page_size=128 — it must
    # be the hottest chain after 16 shared-header admissions
    assert cs["top_prefixes"][0]["depth"] >= 2, cs["top_prefixes"][0]
    if evictions:
        forcers = {f for row in cs["eviction_matrix"].values()
                   for f in row}
        assert "flood" in forcers, (
            f"evictions ran but the flood tenant forced none: "
            f"{cs['eviction_matrix']}")
    out = {
        "cache_hit_rate": cs["prefix"]["hit_rate"],
        "cache_evictions_per_1k_tok": 1e3 * evictions / max(total, 1),
        "cache_saved_tokens_team_a": led["team-a"]["saved_tokens"],
        "cache_saved_tokens_team_b": led["team-b"]["saved_tokens"],
        "cache_saved_tokens_flood": led["flood"]["saved_tokens"],
        "cache_evicted_pages_team_a": led["team-a"]["evicted_pages"],
        "cache_top_prefix_hits": cs["top_prefixes"][0]["hits"],
        "prefix_churn_tok_s": total / dt,
    }
    print(f"[serving_bench] prefix_cache_churn: hit_rate "
          f"{out['cache_hit_rate']:.3f}, "
          f"{out['cache_evictions_per_1k_tok']:.1f} evictions/1k tok, "
          f"saved a/b/flood: {out['cache_saved_tokens_team_a']}/"
          f"{out['cache_saved_tokens_team_b']}/"
          f"{out['cache_saved_tokens_flood']}", flush=True)
    return out


def _spec_churn_bench(params, base, infer_cfg):
    """Speculation composed with stall-free batching, the PR 9 win: a
    three-way A/B under admission churn on a repetition-heavy prompt
    mix (the n-gram sweet spot — code/tables-like local repetition):

      * `churn_spec_*`            — mixed + ADAPTIVE n-gram speculation
                                    (the default controller);
      * `churn_spec_mixed_plain_*` — mixed, no speculation (what the
                                    speculative arm must beat for the
                                    window to pay under churn);
      * `churn_spec_alternating_spec_*` — alternating + fixed-length
                                    n-gram speculation (paying the
                                    churn cliff mixed batching fixed).

    A fourth arm, `churn_spec_draft_model_*`, drives the composition
    this PR made POSSIBLE — DRAFT-MODEL speculation under the mixed
    scheduler (pre-PR it silently forced alternating; mixed+n-gram
    always worked) — through the same churn scenario, one fused
    dispatch per iteration. Its accept rate reflects the random-init
    draft here (the controller walks poor acceptors off); trained
    draft-model acceptance is measured by the trained_spec section.

    Every arm reports tok/s, decode-ITL p99 (ms, the equal-latency
    check), and — speculative arms — committed tokens per decode round.
    A final pair measures the ADAPTIVE FLOOR on the random-prompt
    (low-acceptance) mix: `spec_adaptive_floor_ratio` = adaptive-spec
    tok/s / plain tok/s, which must hover ~1.0 — the controller walks
    every slot to plain decode instead of paying dead verify windows.
    Each scenario runs twice (untimed compile warm-up, then timed)."""
    import dataclasses

    import numpy as np

    from cloud_server_tpu.models import transformer

    from cloud_server_tpu.inference.paged_server import PagedInferenceServer

    cfg = dataclasses.replace(base, decode_attention_impl="pallas")
    # greedy so acceptance reflects the model, not sampling noise
    greedy = dataclasses.replace(infer_cfg, temperature=0.0)
    # tiny random-init draft sharing the target's vocab: exercises the
    # fused draft prefill/decode discipline under churn (acceptance is
    # draft-quality dependent; see docstring)
    draft_cfg = dataclasses.replace(
        base, embed_dim=256, num_layers=2, num_heads=4, num_kv_heads=4,
        mlp_dim=1024)
    draft_params = transformer.init_params(draft_cfg, jax.random.key(11))

    def scenario(scheduler, spec, spec_control, rep, draft=False):
        # every arm (and each arm's warm-up vs timed run) draws the
        # IDENTICAL prompt sequence: the A/B ratios must compare
        # schedulers, not prompt-mix noise
        rng = np.random.RandomState(3)

        def mk(n):
            if rep:
                pat = [int(x) for x in rng.randint(1, 30000, size=8)]
                return (pat * (n // 8 + 1))[:n]
            return [int(x) for x in rng.randint(1, 30000, size=n)]
        srv = PagedInferenceServer(
            params, cfg, greedy, max_slots=16, max_context=1024,
            page_size=128, prefill_chunk=256, decode_chunk=8,
            prompt_buckets=[64, 256, 512], scheduler=scheduler,
            spec_drafts=spec, spec_control=spec_control,
            draft_params=draft_params if draft else None,
            draft_cfg=draft_cfg if draft else None)
        assert srv._mixed_enabled == (scheduler == "mixed")
        first = [srv.submit(mk(64), max_new_tokens=256)
                 for _ in range(8)]
        for _ in range(2):
            srv.step()
        t0 = time.perf_counter()
        r0, c0 = srv.decode_rounds, srv.decode_tokens_committed
        waves = []
        # three waves of long admissions while the first batch decodes:
        # the regime where alternating+spec used to stall
        for _ in range(3):
            waves += [srv.submit(mk(400), max_new_tokens=128)
                      for _ in range(4)]
            for _ in range(6):
                srv.step()
        srv.run_until_idle()
        dt = time.perf_counter() - t0
        total = sum(len(r.tokens) for r in first + waves)
        accept = ((srv.decode_tokens_committed - c0)
                  / max(srv.decode_rounds - r0, 1))
        itls = []
        for r in first:
            itls += [b - a for a, b in zip(r.emit_times,
                                           r.emit_times[1:])]
        itls.sort()
        p99 = (itls[min(len(itls) - 1, int(0.99 * len(itls)))]
               if itls else 0.0)
        srv.stop()
        return total / dt, accept, p99 * 1e3

    out = {}
    arms = {
        # (scheduler, spec_drafts, spec_control, repetitive, draft)
        "churn_spec": ("mixed", 3, None, True, False),  # adaptive dflt
        "churn_spec_mixed_plain": ("mixed", 0, False, True, False),
        "churn_spec_alternating_spec": ("alternating", 3, False, True,
                                        False),
        "churn_spec_draft_model": ("mixed", 3, None, True, True),
        "spec_adaptive_random": ("mixed", 3, None, False, False),
        "spec_plain_random": ("mixed", 0, False, False, False),
    }
    for tag, (sched, spec, ctl, rep, draft) in arms.items():
        scenario(sched, spec, ctl, rep, draft)  # warm-up compiles
        tok_s, accept, itl_p99 = scenario(sched, spec, ctl, rep, draft)
        out[f"{tag}_tok_s"] = tok_s
        out[f"{tag}_itl_ms_p99"] = itl_p99
        if spec:
            out[f"{tag}_accept"] = accept
        print(f"[serving_bench] {tag}: {tok_s:.1f} tok/s, itl_p99 "
              f"{itl_p99:.1f} ms"
              + (f", accept {accept:.2f} tok/round" if spec else ""),
              flush=True)
    out["churn_spec_speedup_vs_plain"] = (
        out["churn_spec_tok_s"]
        / max(out["churn_spec_mixed_plain_tok_s"], 1e-9))
    out["churn_spec_speedup_vs_alternating"] = (
        out["churn_spec_tok_s"]
        / max(out["churn_spec_alternating_spec_tok_s"], 1e-9))
    out["spec_adaptive_floor_ratio"] = (
        out["spec_adaptive_random_tok_s"]
        / max(out["spec_plain_random_tok_s"], 1e-9))
    print(f"[serving_bench] churn_spec speedups: "
          f"{out['churn_spec_speedup_vs_plain']:.2f}x vs mixed-plain, "
          f"{out['churn_spec_speedup_vs_alternating']:.2f}x vs "
          f"alternating+spec; adaptive floor "
          f"{out['spec_adaptive_floor_ratio']:.2f}", flush=True)
    return out


def _admission_churn_bench(params, base, infer_cfg):
    """Continuous batching under churn, A/B over the scheduler: requests
    arrive in waves while others decode. "alternating" runs admissions
    (chunked prefill) as separate dispatches interleaved with decode
    dispatches; "mixed" fuses both into one token-budget dispatch per
    iteration (stall-free scheduling — the r6 tentpole).

    Each scenario runs TWICE: once untimed to compile every dispatch
    shape it triggers (r3's churn_tok_s=2.4 timed ~370 s of remote
    Mosaic compiles, not serving), then timed with all shapes warm.
    Reports completed-token throughput, interleaved-decode count, the
    decode throughput SUSTAINED WHILE ADMISSIONS RUN (the number mixed
    scheduling exists to lift — alternating r5 landed only 10 decode
    steps across the whole admission phase), and the request-level
    latencies chunked prefill exists to bound: TTFT for the long
    prompts that land mid-decode, and inter-token-latency percentiles
    for the requests decoding while those admissions run.

    The headline `churn_*` keys are the MIXED run (the default
    scheduler); `churn_*_alternating` / `churn_*_mixed` carry the A/B
    and `churn_mixed_speedup` the ratio."""
    out = {}
    for sched in ("alternating", "mixed"):
        res = _churn_scenario(params, base, infer_cfg, sched)
        out.update({f"{k}_{sched}": v for k, v in res.items()})
        print(f"[serving_bench] {sched}: churn_tok_s "
              f"{res['churn_tok_s']:.1f} decode_tok_s_during_admission "
              f"{res['churn_decode_tok_s_during_admission']:.1f} "
              f"ttft_ms p50/p95: {res['churn_ttft_ms_p50']:.0f}/"
              f"{res['churn_ttft_ms_p95']:.0f} "
              f"itl_ms p50/p99: {res['churn_itl_ms_p50']:.1f}/"
              f"{res['churn_itl_ms_p99']:.1f}", flush=True)
        if sched == "mixed":
            out.update(res)  # headline keys = the default scheduler
    out["churn_mixed_speedup"] = (out["churn_tok_s_mixed"]
                                  / max(out["churn_tok_s_alternating"],
                                        1e-9))
    print(f"[serving_bench] churn_mixed_speedup: "
          f"{out['churn_mixed_speedup']:.2f}x", flush=True)
    return out


def _overlap_churn_bench(params, base, infer_cfg):
    """Async double-buffered scheduler A/B (ROADMAP item 4's
    acceptance measurement): the SAME churn mix on the mixed
    scheduler with the launch-ahead pipeline ON vs OFF.

    The decisive key is `churn_host_gap_frac_overlap_{on,off}`: off
    measures the full serialized host cost per iteration (sweep +
    admission + build + commit + epilogue over duration); on measures
    only the residual tail the overlap could NOT hide (commit +
    launch + epilogue) — per the flight records' phase clocks, not
    inferred from tok/s. `churn_overlap_speedup` is the end-to-end
    tok/s ratio, and the per-phase p50s land alongside so a
    regression is attributable to a specific phase. The overlap-on
    arm also reports how long the device ran ahead of the host
    needing results (`churn_overlap_launch_lead_ms_p50`) and what
    fraction of busy iterations actually pipelined."""
    out = {}
    res = {}
    for tag, ov in (("off", False), ("on", True)):
        r = _churn_scenario(params, base, infer_cfg, "mixed",
                            overlap=ov)
        res[tag] = r
        out.update({f"{k}_overlap_{tag}": v for k, v in r.items()})
        print(f"[serving_bench] overlap_{tag}: churn_tok_s "
              f"{r['churn_tok_s']:.1f} host_gap_frac "
              f"{r['churn_host_gap_frac']:.4f} itl_ms p50/p99: "
              f"{r['churn_itl_ms_p50']:.1f}/"
              f"{r['churn_itl_ms_p99']:.1f}", flush=True)
    out["churn_overlap_speedup"] = (
        res["on"]["churn_tok_s"] / max(res["off"]["churn_tok_s"], 1e-9))
    out["churn_overlap_gap_reduction"] = (
        res["off"]["churn_host_gap_frac"]
        - res["on"]["churn_host_gap_frac"])
    # acceptance: the overlap must MEASURABLY hide host work — the
    # residual serialized host gap strictly below the sequential gap
    # on the same mix. The AssertionError surfaces through the
    # serving-bench section guard as a `churn_overlap_error` key in
    # the bench JSON (the other sections' failure convention), so a
    # regression is visible in the artifact without voiding the
    # headline decode rows — and a CPU rig, where XLA executes
    # idle-queue dispatches inline so overlap cannot show, records
    # the error key instead of a bogus pass.
    
    assert (out["churn_host_gap_frac_overlap_on"]
            < out["churn_host_gap_frac_overlap_off"]), (
        "overlap-on host_gap_frac "
        f"{out['churn_host_gap_frac_overlap_on']:.4f} not below "
        f"overlap-off {out['churn_host_gap_frac_overlap_off']:.4f}")
    print(f"[serving_bench] churn_overlap_speedup: "
          f"{out['churn_overlap_speedup']:.2f}x, host_gap "
          f"{out['churn_host_gap_frac_overlap_off']:.4f} -> "
          f"{out['churn_host_gap_frac_overlap_on']:.4f}", flush=True)
    return out


def _check_span_trees(srv, reqs):
    """Trace-side integrity check (the span analogue of the
    churn_srv_* histogram agreement): a fully-sampled run produced
    exactly ONE span tree per request; each tree's phase spans are
    monotonic and GAP-FREE (every phase starts exactly where the
    previous ended, covering submit -> finish); and the span
    boundaries agree with the request's own externally recorded
    timing (root start == submit_time, first prefill ends at the
    first emit)."""
    from cloud_server_tpu.inference.request_trace import PHASES
    trees = srv.trace_trees()
    assert len(trees) == len(reqs), (
        f"{len(trees)} span trees for {len(reqs)} requests")
    by_id = {t["request_id"]: t for t in trees}
    assert len(by_id) == len(reqs), "duplicate trees for one request"
    for r in reqs:
        root = by_id[r.request_id]["root"]
        assert root["start"] == r.submit_time
        assert root["end"] is not None, "unfinished tree after idle"
        phases = [c for c in root["children"] if c["name"] in PHASES]
        names = [p["name"] for p in phases]
        for want in ("queue", "prefill", "decode", "emit"):
            assert want in names, f"missing {want} in {names}"
        assert phases[0]["start"] == root["start"]
        for a, b in zip(phases, phases[1:]):
            assert a["end"] == b["start"], \
                f"gap between {a['name']} and {b['name']}"
        assert phases[-1]["end"] == root["end"]
        if r.emit_times:
            first_prefill = next(p for p in phases
                                 if p["name"] == "prefill")
            assert first_prefill["end"] == r.emit_times[0]


# Churn-section SLO config (no QoS registry -> every request rides the
# "default" class): generous targets so attainment reads the
# scheduler, not the tunnel's fixed dispatch cost.
_CHURN_SLO_CFG = {
    "windows_s": [60, 300],
    "classes": {"default": {"objective": 0.99, "ttft_s": 2.0,
                            "itl_s": 1.0, "queue_wait_s": 2.0,
                            "e2e_s": 300.0}}}


def _churn_scenario(params, base, infer_cfg, scheduler, overlap=None):
    import dataclasses

    from cloud_server_tpu.inference.paged_server import PagedInferenceServer

    cfg = dataclasses.replace(base, decode_attention_impl="pallas")

    def scenario():
        # max_slots leaves headroom beyond the initial decode batch so a
        # wave admission lands MID-DECODE (the thing TTFT measures here)
        # instead of queueing for a free slot. Tracing at FULL sampling
        # + SLO tracking ride along: the bench is also the standing
        # proof that both layers cost nothing measurable (the
        # dispatch-count regression test pins the zero-dispatch
        # invariant; the A/B here would show any host-side drag).
        srv = PagedInferenceServer(
            params, cfg, infer_cfg, max_slots=16, max_context=1024,
            page_size=128, prefill_chunk=256, decode_chunk=8,
            prompt_buckets=[64, 256, 512], scheduler=scheduler,
            overlap=overlap, tracing=1.0, slo=_CHURN_SLO_CFG)
        mk_prompt = make_prompt_fn(0)

        first = [srv.submit(mk_prompt(64), max_new_tokens=256)
                 for _ in range(8)]
        for _ in range(2):
            srv.step()
        t0 = time.perf_counter()
        interleaved = 0
        dec_tok_adm = 0      # first-batch tokens landed while admitting
        t_adm = 0.0          # wall time of admitting steps
        waves = []
        # three waves of long-prompt arrivals while the first batch decodes
        for _ in range(3):
            waves += [srv.submit(mk_prompt(400), max_new_tokens=128)
                      for _ in range(4)]
            for _ in range(6):
                admitting = bool(srv._jobs) or srv.num_pending > 0
                n0 = sum(len(r.tokens) for r in first)
                ts = time.perf_counter()
                srv.step()
                te = time.perf_counter()
                if admitting:
                    t_adm += te - ts
                    dec_tok_adm += sum(len(r.tokens) for r in first) - n0
                    if srv.active.any():
                        interleaved += 1
        srv.run_until_idle()
        dt = time.perf_counter() - t0
        snap = srv.metrics_snapshot()  # server-side telemetry, pre-stop
        flight = srv.flight_window()
        # one span tree per request, gap-free phases, agrees with the
        # request objects' own timing (full-sampling integrity check)
        _check_span_trees(srv, first + waves)
        slo_rep = srv.slo_report()
        srv.stop()
        return first, waves, dt, interleaved, dec_tok_adm, t_adm, \
            snap, flight, slo_rep

    scenario()  # warm-up: every prefill/decode shape compiles here
    (first, waves, dt, interleaved, dec_tok_adm, t_adm,
     snap, flight, slo_rep) = scenario()

    total = sum(len(r.tokens) for r in first + waves)

    ttfts = [r.emit_times[0] - r.submit_time
             for r in waves if r.emit_times]
    itls = []
    for r in first:
        itls += [b - a for a, b in zip(r.emit_times, r.emit_times[1:])]

    # Server-side lifecycle telemetry vs the external measurement: the
    # in-server TTFT histogram observed emit_times[0] - submit_time at
    # emit time, so its mean over ALL requests must agree with the same
    # quantity recomputed here from the request objects — a disagreement
    # means the telemetry path dropped or double-counted observations.
    from cloud_server_tpu.utils.serving_metrics import (
        histogram_percentile)
    h_ttft = snap["cloud_server_ttft_seconds"]
    h_itl = snap["cloud_server_itl_seconds"]
    ext_ttft = [r.emit_times[0] - r.submit_time
                for r in first + waves if r.emit_times]
    assert h_ttft["count"] == len(ext_ttft), (
        f"server TTFT count {h_ttft['count']} != external "
        f"{len(ext_ttft)}")
    ext_mean = sum(ext_ttft) / len(ext_ttft)
    srv_mean = h_ttft["sum"] / h_ttft["count"]
    assert abs(srv_mean - ext_mean) <= 0.05 * ext_mean + 5e-3, (
        f"server TTFT mean {srv_mean * 1e3:.1f} ms disagrees with "
        f"external {ext_mean * 1e3:.1f} ms")
    util = [rec["budget_utilization"] for rec in flight
            if "budget_utilization" in rec]
    # Iteration-phase profile of the same run: the host-gap fraction
    # is the serialized host cost per iteration — sequential records
    # count every non-device phase; overlapped records (the async
    # scheduler, ROADMAP item 4 — built) count only the residual
    # commit/launch/epilogue tail, with the hidden sweep/admission/
    # build in overlap_ms. The per-record identity host_ms +
    # device_wait_ms + overlap_ms == duration_ms is asserted (the
    # phase clock partitions the iteration by construction).
    ph_recs = [rec for rec in flight if "phases_ms" in rec]
    assert ph_recs, "profiling-enabled run produced no phase records"
    for rec in ph_recs:
        assert abs(rec["host_ms"] + rec["device_wait_ms"]
                   + rec.get("overlap_ms", 0.0)
                   - rec["duration_ms"]) <= 1e-6 * rec["duration_ms"] \
            + 1e-6, f"phase split does not partition the iteration: {rec}"
    host_gap = (sum(r["host_ms"] for r in ph_recs)
                / max(sum(r["duration_ms"] for r in ph_recs), 1e-9))
    phase_keys = {}
    for ph in ("admission", "build", "device", "commit", "launch",
               "epilogue"):
        vals = [r["phases_ms"].get(ph, 0.0) for r in ph_recs]
        phase_keys[f"churn_phase_ms_{ph}_p50"] = pct(vals, 0.50)
    leads = [r["overlap_launch_lead_ms"] for r in ph_recs
             if "overlap_launch_lead_ms" in r]
    if leads:
        phase_keys["churn_overlap_launch_lead_ms_p50"] = pct(leads, 0.50)
        phase_keys["churn_overlap_frac_iterations"] = (
            len(leads) / len(ph_recs))
    # SLO view of the same run (lifetime counts — deterministic, no
    # window-edge sensitivity): default-class attainment per metric
    slo_keys = {}
    for metric in ("ttft", "itl"):
        life = (slo_rep["classes"]["default"]["metrics"][metric]
                ["lifetime"])
        att = life["attainment"]
        slo_keys[f"churn_slo_attainment_{metric}"] = (
            1.0 if att is None else att)
    return {**slo_keys,
            "churn_tok_s": total / dt,
            "churn_decode_steps_during_admission": interleaved,
            "churn_decode_tok_s_during_admission":
                dec_tok_adm / max(t_adm, 1e-9),
            "churn_ttft_ms_p50": pct(ttfts, 0.50) * 1e3,
            "churn_ttft_ms_p95": pct(ttfts, 0.95) * 1e3,
            "churn_itl_ms_p50": pct(itls, 0.50) * 1e3,
            "churn_itl_ms_p99": pct(itls, 0.99) * 1e3,
            # server-side histogram view (validated against external)
            "churn_srv_ttft_ms_mean": srv_mean * 1e3,
            "churn_srv_ttft_ms_p95":
                histogram_percentile(h_ttft, 0.95) * 1e3,
            "churn_srv_itl_ms_p50":
                histogram_percentile(h_itl, 0.50) * 1e3,
            "churn_srv_itl_ms_p99":
                histogram_percentile(h_itl, 0.99) * 1e3,
            "churn_budget_utilization_mean":
                sum(util) / len(util) if util else 0.0,
            # host-gap attribution (iteration_profile.py): the share
            # of each iteration the device idles while the host works
            # — ROADMAP item 4's claimable headroom, per phase
            "churn_host_gap_frac": host_gap,
            **phase_keys}


def _qos_isolation_bench(params, base, infer_cfg):
    """Multi-tenant QoS isolation under overload, A/B over the
    aggressor: a steady "inter" tenant (interactive, weight 3) decodes
    while a "scraper" tenant (batch class, weight 1) floods the queue
    past slot capacity on a page pool sized to force preemption.
    Three runs on the QoS-enabled server geometry, each also tracked
    against per-class SLO targets (`slo_attainment_interactive` /
    `slo_attainment_batch` report the flood run's lifetime TTFT
    attainment per class — the isolation story in SLO terms):

      * aggressor OFF  -> the victim's uncontended tok/s + ITL p99;
      * aggressor ON, QoS ON  -> fair-share admission + priority
        preemption protect the victim (scraper slots are the victims);
      * aggressor ON, QoS OFF -> the FIFO/youngest-preemption control.

    `qos_isolation_ratio` = victim tok/s (aggressor on, QoS on) /
    victim tok/s (aggressor off) — 1.0 is perfect isolation;
    `qos_off_isolation_ratio` is the same ratio for the control, so
    the headline A/B is the gap between the two. Each scenario runs
    twice (untimed compile warm-up, then timed) like the churn bench."""
    import dataclasses

    from cloud_server_tpu.inference.paged_server import PagedInferenceServer

    cfg = dataclasses.replace(base, decode_attention_impl="pallas")
    # "batch" (not best_effort) for the aggressor: victim selection is
    # unchanged — preemption still targets the lowest class first —
    # and the run now exercises BOTH SLO classes the per-class
    # attainment keys report on (slo_attainment_{interactive,batch})
    qos_cfg = {"quantum": 64,
               "tenants": {
                   "inter": {"weight": 3.0, "priority": "interactive"},
                   "scraper": {"weight": 1.0, "priority": "batch"}}}
    slo_cfg = {"windows_s": [60, 300],
               "classes": {
                   "interactive": {"objective": 0.99, "ttft_s": 2.0,
                                   "itl_s": 1.0, "e2e_s": 300.0},
                   "batch": {"objective": 0.9, "ttft_s": 10.0,
                             "e2e_s": 600.0}}}

    def scenario(aggressor: bool, qos):
        # 16 slots x 8 pages/slot worst case = 128; 72 pages forces
        # on-demand preemption once the flood's chains deepen — the
        # regime victim selection (priority vs youngest) decides
        srv = PagedInferenceServer(
            params, cfg, infer_cfg, max_slots=16, max_context=1024,
            page_size=128, prefill_chunk=256, decode_chunk=8,
            prompt_buckets=[64, 256], num_pages=72, qos=qos,
            slo=slo_cfg)
        mk_prompt = make_prompt_fn(0)

        victims = [srv.submit(mk_prompt(64), max_new_tokens=512,
                              tenant="inter") for _ in range(6)]
        for _ in range(2):
            srv.step()
        aggr = ([srv.submit(mk_prompt(64), max_new_tokens=512,
                            tenant="scraper") for _ in range(24)]
                if aggressor else [])
        v0 = sum(len(r.tokens) for r in victims)
        a0 = sum(len(r.tokens) for r in aggr)
        t0 = time.perf_counter()
        for _ in range(16):
            srv.step()
        dt = time.perf_counter() - t0
        v_tok_s = (sum(len(r.tokens) for r in victims) - v0) / dt
        a_tok_s = (sum(len(r.tokens) for r in aggr) - a0) / dt
        itls = []
        for r in victims:
            gaps = [b - a for a, b in zip(r.emit_times, r.emit_times[1:])
                    if b >= t0]
            itls += gaps
        itls.sort()
        p99 = itls[min(len(itls) - 1, int(0.99 * len(itls)))] if itls \
            else 0.0
        # per-class TTFT attainment (lifetime counts: deterministic)
        # BEFORE the cancel sweep pollutes e2e with cancellations
        rep = srv.slo_report()

        def attain(cls):
            m = rep["classes"].get(cls, {}).get("metrics", {})
            att = m.get("ttft", {}).get("lifetime", {}).get("attainment")
            return 1.0 if att is None else att

        for r in victims + aggr:
            r.cancel()
        srv.run_until_idle()
        srv.stop()
        return {"victim_tok_s": v_tok_s, "aggressor_tok_s": a_tok_s,
                "victim_itl_ms_p99": p99 * 1e3,
                "slo_attainment_interactive": attain("interactive"),
                "slo_attainment_batch": attain("batch")}

    out = {}
    # qos=False force-disables (None would fall back to any
    # InferConfig.qos_config, silently turning the control arm on)
    cases = [("alone", False, qos_cfg), ("flood", True, qos_cfg),
             ("flood_noqos", True, False)]
    for tag, aggressor, qos in cases:
        scenario(aggressor, qos)  # warm-up: compile every shape
        res = scenario(aggressor, qos)
        out[f"qos_{tag}_victim_tok_s"] = res["victim_tok_s"]
        out[f"qos_{tag}_itl_ms_p99"] = res["victim_itl_ms_p99"]
        if aggressor:
            out[f"qos_{tag}_aggressor_tok_s"] = res["aggressor_tok_s"]
        if tag == "flood":  # the QoS-on overload run: the per-class
            # SLO view of isolation (lifetime TTFT attainment)
            out["slo_attainment_interactive"] = \
                res["slo_attainment_interactive"]
            out["slo_attainment_batch"] = res["slo_attainment_batch"]
        print(f"[serving_bench] qos_{tag}: victim "
              f"{res['victim_tok_s']:.1f} tok/s, itl p99 "
              f"{res['victim_itl_ms_p99']:.1f} ms, aggressor "
              f"{res['aggressor_tok_s']:.1f} tok/s", flush=True)
    alone = max(out["qos_alone_victim_tok_s"], 1e-9)
    out["qos_isolation_ratio"] = out["qos_flood_victim_tok_s"] / alone
    out["qos_off_isolation_ratio"] = (
        out["qos_flood_noqos_victim_tok_s"] / alone)
    print(f"[serving_bench] qos_isolation_ratio "
          f"{out['qos_isolation_ratio']:.2f} (qos off: "
          f"{out['qos_off_isolation_ratio']:.2f})", flush=True)
    return out


def _trained_spec_bench():
    """Speculative decoding measured on a TRAINED model + natural text.

    r3's acceptance numbers came from an untrained model decoding
    greedily — which collapses to repetition on ANY prompt, so its
    'random-prompt' row measured the same degenerate regime. Here the
    framework's own pipeline (byte tokenizer -> memmap -> training
    loop) trains a small byte-level LM on this repo's source code
    (tests/ held out), plus a 4x-smaller draft model, then serves
    held-out code through the paged server three ways: plain, n-gram
    speculation, and in-server draft-model speculation. Acceptance
    rates are per committed-tokens-per-round (1.0 = no speculation
    win).

    Read the ACCEPT columns, not tok/s: this model is deliberately tiny
    (trainable inside the bench), so serving it is per-dispatch-overhead
    bound and the (G+1)-token verify window (plus G+1 draft forwards on
    the draft row) costs several thin-model forwards' overhead for <3x
    the tokens — speculation cannot pay that back HERE. The 330M
    `decode_tok_s_pallas_spec_*` rows are where the wall-clock win
    lives (weights-streaming-bound, window nearly free); this section's
    job is the acceptance evidence the r3 bench lacked: a TRAINED model
    on natural held-out text (r4 measured: n-gram 1.64, draft-model
    2.63 committed tokens/round; r5, run standalone outside the
    driver's time budget: n-gram 1.58, draft-model 2.78 — stable
    round-over-round)."""
    import dataclasses
    import glob as _glob

    import numpy as np

    from cloud_server_tpu.config import InferConfig, ModelConfig, TrainConfig
    from cloud_server_tpu.inference.paged_server import PagedInferenceServer
    from cloud_server_tpu.parallel.mesh import make_mesh
    from cloud_server_tpu.config import MeshConfig
    from cloud_server_tpu.training import init_train_state, make_train_step

    here = os.path.dirname(os.path.abspath(__file__))
    src = sorted(_glob.glob(os.path.join(here, "cloud_server_tpu", "**",
                                         "*.py"), recursive=True))
    corpus = b"".join(open(f, "rb").read() for f in src)
    held = sorted(_glob.glob(os.path.join(here, "tests", "*.py")))
    held_text = b"".join(open(f, "rb").read() for f in held)
    data = np.frombuffer(corpus, np.uint8).astype(np.int32)

    seq = 256

    def train_one(cfg, steps, seed):
        mesh = make_mesh(MeshConfig())
        tcfg = TrainConfig(batch_size=16, seq_len=seq, warmup_steps=20,
                           total_steps=steps, learning_rate=3e-3)
        state = init_train_state(cfg, tcfg, mesh, jax.random.key(seed))
        step, batch_sharding = make_train_step(cfg, tcfg, mesh)
        rng = np.random.RandomState(seed)
        loss = None
        for i in range(steps):
            starts = rng.randint(0, len(data) - seq - 1, size=16)
            toks = np.stack([data[s:s + seq] for s in starts])
            state, metrics = step(state, {"tokens": jnp.asarray(toks)})
            if i == steps - 1:
                loss = float(jax.device_get(metrics["loss"]))
        print(f"[trained_spec] trained {cfg.num_layers}L/"
              f"{cfg.embed_dim}d {steps} steps, final loss {loss:.3f}",
              flush=True)
        return jax.device_get(state.params)

    target_cfg = ModelConfig(
        vocab_size=259, embed_dim=256, num_layers=4, num_heads=4,
        num_kv_heads=4, head_dim=64, mlp_dim=1024, max_seq_len=1024,
        dtype="bfloat16", param_dtype="float32", remat="none")
    draft_cfg = dataclasses.replace(target_cfg, embed_dim=128,
                                    num_layers=1, mlp_dim=512)
    t_params = train_one(target_cfg, 400, 0)
    d_params = train_one(draft_cfg, 400, 1)

    # held-out natural prompts: code text the model never trained on
    hrng = np.random.RandomState(3)
    prompts = []
    for _ in range(8):
        s = hrng.randint(0, len(held_text) - 129)
        prompts.append([int(b) for b in held_text[s:s + 128]])
    greedy = InferConfig(max_decode_len=256, temperature=0.0,
                         eos_token_id=-1, pad_token_id=0)
    serve_cfg = dataclasses.replace(target_cfg,
                                    decode_attention_impl="pallas")

    out = {}

    def run(tag, spec, draft=False):
        srv = PagedInferenceServer(
            t_params, serve_cfg, greedy, max_slots=8, max_context=512,
            page_size=128, prefill_chunk=256, decode_chunk=16,
            spec_drafts=spec, prompt_buckets=[128],
            draft_params=d_params if draft else None,
            draft_cfg=draft_cfg if draft else None)

        def full_run():
            for p in prompts:
                srv.submit(p, max_new_tokens=256)
            before, r0, c0 = (srv.tokens_emitted, srv.decode_rounds,
                              srv.decode_tokens_committed)
            t0 = time.perf_counter()
            srv.run_until_idle()
            dt = time.perf_counter() - t0
            return (srv.tokens_emitted - before,
                    srv.decode_rounds - r0,
                    srv.decode_tokens_committed - c0, dt)

        # untimed pass compiles every dispatch the run triggers — the
        # round count shrinks (16 -> 8 -> ... -> 1) as budgets drain,
        # and each count is its own remote Mosaic compile; the timed
        # pass then measures serving, not compilation
        full_run()
        toks, rounds, committed, dt = full_run()
        out[tag] = toks / dt
        if spec:
            out[tag + "_accept"] = committed / max(rounds, 1)
            print(f"[trained_spec] {tag}: {out[tag]:.1f} tok/s, "
                  f"accept {out[tag + '_accept']:.2f}", flush=True)
        else:
            print(f"[trained_spec] {tag}: {out[tag]:.1f} tok/s",
                  flush=True)
        srv.stop()

    run("trained_tok_s_plain", 0)
    run("trained_tok_s_ngram_spec", 3)
    run("trained_tok_s_draft_spec", 3, draft=True)
    return out


def _hbm_bps() -> float:
    """This device's HBM bandwidth for the physical-sanity filter.
    Known parts only; an UNKNOWN device kind returns 0.0, which
    DISABLES rejection (floor 0) — on a part we can't bound, clamping
    to the wrong roofline would fabricate numbers instead of
    measuring them."""
    kind = jax.devices()[0].device_kind.lower()
    for key, bps in (("v5 lite", 0.819e12), ("v5e", 0.819e12),
                     ("v5p", 2.765e12), ("v5", 1.228e12),
                     ("v6e", 1.638e12), ("trillium", 1.638e12),
                     ("v4", 1.228e12), ("v3", 0.9e12)):
        if key in kind:
            return bps
    return 0.0


def _robust_attn_us(make_body, q, bytes_read: float,
                    n_meas: int = 5) -> tuple[float, float, int]:
    """(median_us, relative spread, n_rejected) over `n_meas`
    differential estimates, REJECTING the physically impossible: an
    estimate implying more than ~1.1x the HBM roofline's bandwidth for
    `bytes_read` is a harness artifact, not a measurement (r3 published
    12.0 us for a 33.6 MB read — 2.8 TB/s on a 0.8 TB/s part — and it
    rode into the round's headline). Spread is (max-min)/median of the
    survivors; callers treat spread > 0.5 as 'do not quote'."""
    from jax import lax

    def scan_of(n):
        def fn(q0):
            def f(qq, _):
                return make_body(qq).astype(qq.dtype), None
            return lax.scan(f, q0, None, length=n)[0]
        return fn

    # 100/1600: at ~50-500 us/iter the 1500-iter delta dwarfs the
    # tunnel's fixed-cost variance (negative estimates otherwise)
    ests = diff_time_scan_multi(scan_of, (q,), 100, 1600, reps=3,
                                n_meas=n_meas)
    bps = _hbm_bps()
    floor_s = bytes_read / (bps * 1.1) if bps > 0 else 0.0
    ok = [e for e in ests if e >= floor_s]
    rejected = len(ests) - len(ok)
    if not ok:  # all impossible: report the floor-clamped median, loudly
        med = sorted(ests)[len(ests) // 2]
        return max(med, floor_s) * 1e6, 999.0, rejected
    med = sorted(ok)[len(ok) // 2]
    spread = (max(ok) - min(ok)) / med if med > 0 else 999.0
    return med * 1e6, spread, rejected


def _longcontext_attention_bench():
    """Decode attention, paged kernel vs XLA dense, differential scan
    timing (tunnel-free) with roofline-rejected repeats (see
    _robust_attn_us). Three cases:
      * S=1024 full-length (B=8) — XLA's best shape, near roofline;
        parity expected (r3/r4 history: see docs/serving.md).
      * S=8192 full-length (B=2) — long-context decode.
      * RAGGED S=1024 (B=8, true lens 128..1024) — the shape the paged
        kernel exists for: it reads only each row's true pages while
        dense attention streams the full padded (B, S) KV. This is the
        serving steady state (requests at mixed depths), and the row the
        kernel's length-bounded claim is judged by."""
    import numpy as np

    from cloud_server_tpu.ops.attention import causal_attention
    from cloud_server_tpu.ops.paged_attention import paged_attention

    out = {}
    KH = H = 16
    D, PS = 64, 128
    cases = [("attn1k", 1024, 8, None),
             ("attn8k", 8192, 2, None),
             ("attn_ragged", 1024, 8,
              [128, 256, 384, 512, 640, 768, 896, 1024])]
    for tag, S, b, true_lens in cases:
        try:
            _attn_case(out, tag, S, b, true_lens, KH, H, D, PS)
        except Exception as exc:  # noqa: BLE001 — tunnel flakes: keep
            # the cases already measured (r5 lost attn8k+ragged to one
            # remote-compile drop that voided the whole section)
            print(f"[serving_bench] {tag} skipped after error: {exc!r}",
                  flush=True)
            out[f"{tag}_error"] = repr(exc)[:160]
    return out


def _attn_case(out, tag, S, b, true_lens, KH, H, D, PS):
    import numpy as np

    from cloud_server_tpu.ops.attention import causal_attention
    from cloud_server_tpu.ops.paged_attention import paged_attention

    mp = S // PS
    num_pages = b * mp
    ks = jax.random.split(jax.random.key(1), 4)
    k_pool = jax.random.normal(ks[0], (1, num_pages, KH, D, PS),
                               jnp.bfloat16)
    v_pool = jax.random.normal(ks[1], (1, num_pages, KH, D, PS),
                               jnp.bfloat16)
    tables = jnp.asarray(
        np.random.RandomState(0).permutation(num_pages).reshape(b, mp),
        jnp.int32)
    k_cat = jax.random.normal(ks[2], (b, S, KH, D), jnp.bfloat16)
    v_cat = jax.random.normal(ks[3], (b, S, KH, D), jnp.bfloat16)
    lens = jnp.asarray(true_lens if true_lens is not None
                       else [S] * b, jnp.int32)
    q = jax.random.normal(ks[2], (b, 1, H, D), jnp.bfloat16)

    # K+V bf16 bytes actually required: the kernel reads page-rounded
    # true lengths; dense XLA streams the full padded extent
    kern_tokens = sum(-(-int(l) // PS) * PS for l in lens)
    kern_bytes = 2 * kern_tokens * KH * D * 2
    xla_bytes = 2 * b * S * KH * D * 2

    us_k, sp_k, rej_k = _robust_attn_us(
        lambda qq: paged_attention(qq, k_pool, v_pool, lens, tables,
                                   0, pages_per_block=8,
                                   interpret=False),
        q, kern_bytes)
    us_x, sp_x, rej_x = _robust_attn_us(
        lambda qq: causal_attention(qq, k_cat, v_cat,
                                    q_positions=(lens - 1)[:, None],
                                    kv_length=lens),
        q, xla_bytes)
    out[f"{tag}_us_pallas"] = us_k
    out[f"{tag}_us_xla"] = us_x
    out[f"{tag}_spread"] = round(max(sp_k, sp_x), 3)
    if rej_k or rej_x:
        out[f"{tag}_rejected_samples"] = rej_k + rej_x
    if true_lens is not None:
        out[f"{tag}_kernel_speedup"] = round(us_x / us_k, 3)
    print(f"[serving_bench] {tag} pallas/xla us: {us_k:.1f}/{us_x:.1f}"
          f" spread {max(sp_k, sp_x):.2f}"
          f" rejected {rej_k + rej_x}", flush=True)
    return out


def main() -> None:
    """Headline-first protocol: the driver tail-parses the LAST complete
    JSON line, and its time budget is finite — r4 learned this the hard
    way (rc=124 with the only print at the very end: no parsed number
    for the round). So the headline line is printed IMMEDIATELY after
    train_bench, then RE-printed with richer extras after every section
    that completes — a timeout or tunnel flake mid-section still leaves
    a valid, maximally-enriched earlier line. The expensive trained-spec
    section (trains two models in-bench; its r4 acceptance numbers —
    n-gram 1.64, draft 2.63 — are kept in its docstring as provenance)
    runs LAST and only inside the time budget."""
    t_start = time.perf_counter()
    base_tag, base = _baseline_tokens_per_sec()

    train = train_bench()
    extra = {
        "step_time_ms": round(train["step_time_ms"], 2),
        "approx_mfu": round(train["approx_mfu"], 4),
        "device": str(jax.devices()[0]),
        "baseline_round": base_tag,
    }

    def emit() -> None:
        # ONE self-contained JSON line per call, atomically flushed
        print(json.dumps({
            "metric": "train_tokens_per_sec_330M_bf16",
            "value": round(train["tokens_per_sec"], 1),
            "unit": "tokens/s",
            "vs_baseline": (round(train["tokens_per_sec"] / base, 4)
                            if base > 0 else 1.0),
            "extra": extra,
        }), flush=True)

    emit()  # the driver has a parsed headline (incl. MFU) from here on

    def section(name: str, skip_env: str | None, fn, ndigits: int) -> None:
        if skip_env and os.environ.get(skip_env) == "1":
            return
        try:
            rows = fn()
        except Exception as exc:  # noqa: BLE001 — tunnel flakes happen
            print(f"[bench] section {name} skipped after error: {exc!r}",
                  flush=True)
            extra[f"{name}_error"] = repr(exc)[:200]
        else:
            extra.update({k: round(v, ndigits) if isinstance(v, float)
                          else v for k, v in rows.items()})
        emit()

    section("longseq", "BENCH_SKIP_LONGSEQ", longseq_attention_bench, 2)
    section("serving", "BENCH_SKIP_SERVING", serving_bench, 1)
    section("longcontext_attn", "BENCH_SKIP_SERVING",
            _longcontext_attention_bench, 2)

    budget_s = float(os.environ.get("BENCH_TIME_BUDGET_S", "1500"))
    elapsed = time.perf_counter() - t_start
    if os.environ.get("BENCH_SKIP_SERVING") != "1" and elapsed < budget_s:
        section("trained_spec", None, _trained_spec_bench, 1)
    else:
        extra["trained_spec_skipped_at_s"] = round(elapsed, 1)
        emit()


if __name__ == "__main__":
    main()

"""Benchmark harness — runs on the real TPU chip.

Times the full jitted training step (fwd+bwd+optimizer) of a ~330M-param
dense decoder LM in bfloat16 and prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference (view-sonic/Cloud-Server @ v0) publishes no numbers
(BASELINE.md: empty working tree), so `vs_baseline` is computed against the
previous round's own result (BENCH_r01.json: 26,249.5 tok/s on this same
config) — round-over-round regression tracking rather than a constant 1.0.

Config notes (measured on TPU v5e, this repo):
  * attention_impl="flash" + remat="dots" (with the flash residuals saved
    via checkpoint_name): 312 -> ~229 ms/step vs the r1 XLA-attention path.
  * the S=2048 extra compares the pallas flash kernel against XLA dense
    attention at long sequence in a training-style fwd+bwd.
  * r2 sweep results at this config (kept for provenance, all slower or
    invalid): vocab_chunk 4k/8k ~+4%, remat="attn" ~+4%, flash blocks
    512/512 +10% (the 1024 single-block fused-bwd path wins), remat="none"
    fails to compile even with flash, bf16 master params -5% but changes
    optimizer numerics. Step decomposition: fwd 62 ms, bwd ~145 ms,
    optimizer 18 ms (near bandwidth-bound: ~9 GB of f32 param/moment
    traffic).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp

def _baseline_tokens_per_sec() -> float:
    """Previous round's measured tokens/s (same config & chip), read from
    BENCH_r01.json so a regenerated baseline can't silently diverge from a
    hardcoded copy. Falls back to 1:1 if the file is missing."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_r01.json")
    try:
        with open(path) as f:
            return float(json.load(f)["parsed"]["value"])
    except (OSError, KeyError, ValueError, TypeError):
        return 0.0


def _sync(state, metrics) -> float:
    """Force completion of everything queued: metrics loss AND a state leaf
    (the optimizer update may still be in flight after the loss is ready)."""
    loss = float(metrics["loss"])
    int(jax.device_get(state.step))
    return loss


def train_bench():
    from cloud_server_tpu.config import MeshConfig, ModelConfig, TrainConfig
    from cloud_server_tpu.parallel.mesh import make_mesh
    from cloud_server_tpu.training import init_train_state, make_train_step

    model_cfg = ModelConfig(
        vocab_size=32000, embed_dim=1024, num_layers=16, num_heads=16,
        num_kv_heads=16, head_dim=64, mlp_dim=4096, max_seq_len=1024,
        dtype="bfloat16", param_dtype="float32", remat="dots",
        attention_impl="flash")
    batch, seq = 8, 1024
    train_cfg = TrainConfig(batch_size=batch, seq_len=seq, warmup_steps=10,
                            total_steps=100)

    mesh = make_mesh(MeshConfig())  # single chip
    state = init_train_state(model_cfg, train_cfg, mesh, jax.random.key(0))
    step, batch_sharding = make_train_step(model_cfg, train_cfg, mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (batch, seq), 0,
                           model_cfg.vocab_size), batch_sharding)
    data = {"tokens": tokens}

    for _ in range(3):
        state, metrics = step(state, data)
    _sync(state, metrics)

    n_steps = 20
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, data)
    loss_val = _sync(state, metrics)
    dt = time.perf_counter() - t0
    if loss_val != loss_val:
        raise SystemExit("bench invalid: loss is NaN")

    tokens_per_sec = batch * seq * n_steps / dt

    # Rough MFU: 6 * non-embedding params * tokens for fwd+bwd, vs 197
    # TFLOP/s bf16 peak (TPU v5e).
    n_layer_params = model_cfg.num_layers * (
        4 * model_cfg.embed_dim * model_cfg.num_heads * model_cfg.head_dim
        + 3 * model_cfg.embed_dim * model_cfg.mlp_dim)
    n_embed = 2 * model_cfg.vocab_size * model_cfg.embed_dim
    flops_per_token = 6 * (n_layer_params + n_embed)
    mfu = flops_per_token * tokens_per_sec / 197e12

    return {
        "tokens_per_sec": tokens_per_sec,
        "step_time_ms": 1000 * dt / n_steps,
        "approx_mfu": mfu,
    }


def longseq_attention_bench():
    """Training-style fwd+bwd through a 4-layer stack at S=2048:
    pallas flash kernel vs XLA dense attention."""
    import dataclasses

    from cloud_server_tpu.config import ModelConfig
    from cloud_server_tpu.models import transformer

    base = ModelConfig(
        vocab_size=8192, embed_dim=1024, num_layers=4, num_heads=16,
        num_kv_heads=16, head_dim=64, mlp_dim=4096, max_seq_len=2048,
        dtype="bfloat16", param_dtype="float32", remat="dots")
    tokens = jax.random.randint(jax.random.key(2), (4, 2048), 0,
                                base.vocab_size)
    batch = {"tokens": tokens}

    out = {}
    for impl in ("flash", "xla"):
        cfg = dataclasses.replace(base, attention_impl=impl)
        params = transformer.init_params(cfg, jax.random.key(0))

        @jax.jit
        def grad_fn(params, batch, cfg=cfg):
            def loss(p):
                l, _ = transformer.next_token_loss(p, batch, cfg)
                return l
            return jax.grad(loss)(params)

        g = grad_fn(params, batch)
        float(jax.tree.leaves(g)[0].reshape(-1)[0].astype(jnp.float32))
        n = 10
        t0 = time.perf_counter()
        for _ in range(n):
            g = grad_fn(params, batch)
        float(jax.tree.leaves(g)[0].reshape(-1)[0].astype(jnp.float32))
        out[impl] = 1000 * (time.perf_counter() - t0) / n
    return {"s2048_fwdbwd_flash_ms": out["flash"],
            "s2048_fwdbwd_xla_ms": out["xla"],
            "s2048_flash_speedup": out["xla"] / out["flash"]}


def serving_bench():
    """Steady-state continuous-batching decode through InferenceServer on
    the 330M model: 8 slots x 1024 cache, xla vs pallas decode attention,
    bf16 vs int8 weights. Decode is HBM-bound (weights + cache streamed per
    token), which is exactly what the pallas decode kernel and int8
    quantization exist to cut — this measures both claims."""
    import dataclasses

    from cloud_server_tpu.config import InferConfig, ModelConfig
    from cloud_server_tpu.inference.server import InferenceServer
    from cloud_server_tpu.models import transformer
    from cloud_server_tpu.models.quantization import quantize_params

    base = ModelConfig(
        vocab_size=32000, embed_dim=1024, num_layers=16, num_heads=16,
        num_kv_heads=16, head_dim=64, mlp_dim=4096, max_seq_len=1024,
        dtype="bfloat16", param_dtype="float32", remat="none")
    infer_cfg = InferConfig(max_decode_len=900, temperature=1.0,
                            eos_token_id=-1, pad_token_id=0)
    params_bf16 = transformer.init_params(base, jax.random.key(0))
    params_int8 = quantize_params(params_bf16)
    prompts = [list(range(1, 65)) for _ in range(8)]

    chunk = 32  # multi-token scheduling: one host sync per 32 decode steps
    weights = {"bf16": params_bf16, "int8": params_int8}
    modes = [(impl, wname, "model") for impl in ("xla", "pallas")
             for wname in ("bf16", "int8")]
    modes.append(("xla", "bf16", "int8"))     # int8 KV, dequant outside
    modes.append(("pallas", "bf16", "int8"))  # int8 KV, dequant in VMEM
    out = {}
    for impl, wname, kv in modes:
        cfg = dataclasses.replace(base, decode_attention_impl=impl,
                                  kv_cache_dtype=kv)
        srv = InferenceServer(weights[wname], cfg, infer_cfg, max_slots=8,
                              max_len=1024, prompt_buckets=[64],
                              decode_chunk=chunk)
        for p in prompts:
            srv.submit(p, max_new_tokens=900)
        for _ in range(3):  # admit + warm the decode jit
            srv.step()
        n = 8
        tokens_before = sum(len(r.tokens) for r in srv._slots if r)
        t0 = time.perf_counter()
        for _ in range(n):
            srv.step()
        dt = time.perf_counter() - t0
        tokens_after = sum(len(r.tokens) for r in srv._slots if r)
        tag = f"decode_tok_s_{impl}_{wname}" + (
            "_kvint8" if kv == "int8" else "")
        out[tag] = (tokens_after - tokens_before) / dt
        del srv, cfg
    return out


def main() -> None:
    train = train_bench()
    extra = {
        "step_time_ms": round(train["step_time_ms"], 2),
        "approx_mfu": round(train["approx_mfu"], 4),
        "device": str(jax.devices()[0]),
    }
    if os.environ.get("BENCH_SKIP_LONGSEQ") != "1":
        extra.update({k: round(v, 2) for k, v in
                      longseq_attention_bench().items()})
    if os.environ.get("BENCH_SKIP_SERVING") != "1":
        extra.update({k: round(v, 1) for k, v in serving_bench().items()})

    base = _baseline_tokens_per_sec()
    print(json.dumps({
        "metric": "train_tokens_per_sec_330M_bf16",
        "value": round(train["tokens_per_sec"], 1),
        "unit": "tokens/s",
        "vs_baseline": (round(train["tokens_per_sec"] / base, 4)
                        if base > 0 else 1.0),
        "extra": extra,
    }))


if __name__ == "__main__":
    main()

"""Benchmark harness — runs on the real TPU chip.

Times the full jitted training step (fwd+bwd+optimizer) of a ~330M-param
dense decoder LM in bfloat16 and prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference (view-sonic/Cloud-Server @ v0) publishes no numbers
(BASELINE.md: empty working tree), so vs_baseline is reported as 1.0 by
definition against an empty baseline; the absolute tokens/sec and MFU are
the numbers that matter round-over-round.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp


def main() -> None:
    from cloud_server_tpu.config import MeshConfig, ModelConfig, TrainConfig
    from cloud_server_tpu.parallel.mesh import make_mesh
    from cloud_server_tpu.training import init_train_state, make_train_step

    model_cfg = ModelConfig(
        vocab_size=32000, embed_dim=1024, num_layers=16, num_heads=16,
        num_kv_heads=16, head_dim=64, mlp_dim=4096, max_seq_len=1024,
        dtype="bfloat16", param_dtype="float32", remat="full")
    batch, seq = 8, 1024
    train_cfg = TrainConfig(batch_size=batch, seq_len=seq, warmup_steps=10,
                            total_steps=100)

    mesh = make_mesh(MeshConfig())  # single chip
    state = init_train_state(model_cfg, train_cfg, mesh, jax.random.key(0))
    step, batch_sharding = make_train_step(model_cfg, train_cfg, mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(1), (batch, seq), 0,
                           model_cfg.vocab_size), batch_sharding)
    data = {"tokens": tokens}

    # Warmup / compile. float() forces a device->host transfer, which is a
    # true sync even on backends where block_until_ready returns early
    # (observed on the tunneled 'axon' platform).
    for _ in range(3):
        state, metrics = step(state, data)
    float(metrics["loss"])

    n_steps = 20
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, data)
    loss_val = float(metrics["loss"])
    dt = time.perf_counter() - t0
    if loss_val != loss_val:
        raise SystemExit("bench invalid: loss is NaN")

    tokens_per_sec = batch * seq * n_steps / dt

    # Rough MFU: 6 * non-embedding params * tokens for fwd+bwd, vs 197
    # TFLOP/s bf16 peak (TPU v5e).
    n_layer_params = model_cfg.num_layers * (
        4 * model_cfg.embed_dim * model_cfg.num_heads * model_cfg.head_dim
        + 3 * model_cfg.embed_dim * model_cfg.mlp_dim)
    n_embed = 2 * model_cfg.vocab_size * model_cfg.embed_dim
    flops_per_token = 6 * (n_layer_params + n_embed)
    mfu = flops_per_token * tokens_per_sec / 197e12

    print(json.dumps({
        "metric": "train_tokens_per_sec_330M_bf16",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": 1.0,
        "extra": {"step_time_ms": round(1000 * dt / n_steps, 2),
                  "approx_mfu": round(mfu, 4),
                  "device": str(jax.devices()[0])},
    }))


if __name__ == "__main__":
    main()
